"""Single-launch fit step (docs/TRAINING.md).

The eager Module fit step costs ~32 device launches: one fused fwd+bwd
program (executor.py) plus one compiled program per kvstore bucket
(kvstore_fused.py), with a blocking ``asnumpy`` in ``update_metric``
every batch. ``FusedFitStep`` collapses all of it into ONE jitted XLA
program per step for eligible configurations:

    forward + backward (jax.vjp over the compiled graph_fn)
      -> 2-bit quantize with donated error-feedback residual (optional)
      -> cross-device reduce (GSPMD psum when the batch is mesh-sharded)
      -> fused optimizer apply (Optimizer._fused_fit_sig)
      -> device-side metric accumulation (EvalMetric.device_fn)

Parameters, optimizer state, residuals, aux states, and the metric
accumulator are DONATED, so HBM holds one copy of the training state and
a steady-state step is a single device launch with zero host syncs —
the same shape as parallel/trainer.py's TrainStep, brought to the
Module/kvstore path that ``fit``, ``model.py``, and user scripts use.

Eligibility (checked once per optimizer init, cheaply re-checked per
batch): dense f32/f16/bf16 params with grad_req='write', an optimizer
describing its update via the shared fused-update protocol
(``_fused_fit_sig`` non-None — SGD, Adam, LAMB, RMSProp, AdaGrad,
Adamax, Nadam, LBSGD, each with or without multi-precision
``(inner, weight32)`` master-weight state), a local/device kvstore (or
none) with or without 2-bit compression, no installed monitor, no
inputs_need_grad. Everything else falls back to the eager fwd_bwd +
bucketed-kvstore path unchanged; error-feedback residuals move between
the two paths through the same spill/reseed mechanism the bucketed
engine uses, so no accumulated residual is lost.

Low-precision (bf16/f16) training is first-class: master weights and
optimizer state stay f32 inside the same donated program, 2-bit
residuals operate on the f32 master-gradient view, and a
``DynamicLossScaler`` (fused_update.py) rides along — its scale is a
runtime scalar, the inf/nan overflow check is folded into the program,
and the skip-update decision is a ``lax.cond``, so overflow handling
costs zero host syncs.

The compiled step is cached per SYMBOL (sharing executables across
rebinds like executor._compiled_cache) and keyed by everything that
changes the program — param set, compression threshold, optimizer
signature, state templates, multi-precision flags, metric signature,
loss-scaler config. ``rescale_grad``, lr, wd, per-key extra scalars,
and the loss scale ride as runtime arguments, and jax's shape-keyed
jit cache handles ragged final batches: each distinct batch shape
traces once (``TRACE_COUNT``), steady state never retraces.
"""
from __future__ import annotations

import os
import weakref

import numpy as _np
import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from .. import fused_update as _fused
from .. import optimizer as opt_mod
from .. import telemetry as _telemetry
from ..kvstore import KVStore, _updater_key
from ..kvstore_fused import two_bit_quantize
from ..executor import _compiled_cache, _count_dispatch
from ..model import _local_updater_key

__all__ = ["FusedFitStep", "TRACE_COUNT"]


def _fusable_kv(kv):
    """Stores whose reduce can live INSIDE the fit program: the plain
    local store, and kvstore='tpu' when compiled programs may span its
    world (every single-process world; multi-process only on backends
    whose XLA runtime executes cross-process programs — on the CPU
    backend a multi-process tpu kvstore keeps the eager fwd_bwd +
    collective-push path instead)."""
    from ..kvstore_tpu import KVStoreTPU
    if type(kv) is KVStore:
        return True
    return isinstance(kv, KVStoreTPU) and kv._gspmd_ok


def _global_fit_mesh(kv, n_local):
    """The 'dp' mesh of a multi-process fused fit step: every process
    contributes its first ``n_local`` devices, so the global batch
    shards process-major and the vjp's gradient psum spans hosts."""
    from ..kvstore_tpu import KVStoreTPU
    if not isinstance(kv, KVStoreTPU) or kv.num_workers == 1:
        return None
    from jax.sharding import Mesh
    devs = []
    for p in range(jax.process_count()):
        mine = [d for d in jax.devices() if d.process_index == p][:n_local]
        if len(mine) < n_local:
            return False        # a process with fewer devices: not fusable
        devs.extend(mine)
    # analyze: ok(hostsync) mesh construction from host device handles, once per build, no device data
    return Mesh(_np.array(devs), ("dp",))

# incremented inside the step function at trace time only; steady-state
# steps (including repeats of a ragged batch shape) leave it untouched.
# The count lives in the mx.telemetry registry (fit_step_retraces); the
# module-level ``TRACE_COUNT`` name stays a live alias via __getattr__.
FIT_RETRACES = _telemetry.REGISTRY.counter(
    "fit_step_retraces",
    "fused fit-step program (re)traces (the TRACE_COUNT witness)",
    vital=True)
# shared RetraceSite semantics with executor / kvstore_fused: the step
# body calls _note_retrace() at trace time; the launch times through it
_SITE = _telemetry.RetraceSite(FIT_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="fit_step")
_note_retrace = _SITE.note


def __getattr__(name):
    if name == "TRACE_COUNT":
        return int(FIT_RETRACES.value)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


# sample an HBM StepMemoryTracker every N fused launches (0 = off; a
# live-array census per step is not free on the host)
_MEM_EVERY = int(os.environ.get("MXNET_TELEMETRY_MEMORY_EVERY", "0") or 0)


def _sentinel_enabled():
    """In-launch numerics sentinels (docs/OBSERVABILITY.md): a handful
    of scalars — global grad norm, non-finite element count, metric
    EMA z-score, residual-norm drift — folded into the SAME donated
    program and read only at sync boundaries. On by default (the
    overhead contract is zero extra dispatches/syncs and <2% step
    time, gated by bench.py); ``MXNET_SENTINEL_NUMERICS=0`` disables."""
    from ..telemetry.sentinel import numerics_enabled
    return numerics_enabled()


# EMA decay for the sentinel metric/residual baselines, and how many
# steps the z-score stays muted while the baseline converges
_SENT_DECAY = 0.98
_SENT_WARMUP = 8.0


def _metric_closure(metric, label_names, output_names):
    """(metric_fn, cache_sig) folding ``metric``'s device accumulation
    into the step program with ``update_dict``'s output/label selection
    semantics; (None, None) when the metric accumulates on the host."""
    fn = metric.device_fn() if metric is not None else None
    if fn is None:
        return None, None
    out_sel = tuple(metric.output_names) if metric.output_names else None
    lab_sel = tuple(metric.label_names) if metric.label_names else None
    label_names = tuple(label_names)
    output_names = tuple(output_names)

    def metric_fn(inputs, outs):
        pred_d = dict(zip(output_names, outs))
        preds = ([pred_d[n] for n in out_sel if n in pred_d]
                 if out_sel is not None else list(outs))
        names = lab_sel if lab_sel is not None else label_names
        labels = [inputs[n] for n in names if n in inputs]
        return fn(labels, preds)

    sig = (type(metric).__name__, metric.device_sig(), out_sel, lab_sel,
           label_names, output_names)
    return metric_fn, sig


def _build_fit_program(graph_fn, param_order, threshold, mode, tpls,
                       mp_flags, use_wd, metric_fn, mirror, scaler,
                       sentinel=False):
    """ONE jitted program: fwd+bwd+compress+reduce+update(+metric).

    The compress and optimizer math are the SAME functions the bucketed
    kvstore step compiles (kvstore_fused.two_bit_quantize and the
    fused_update builder, themselves mirroring ops/optimizer_ops.py),
    so fused weights match the eager path within FMA-contraction ulps
    (tests/test_fused_fit.py pins the tolerance).

    With a loss scaler, the entire compress+update block sits under a
    ``lax.cond`` on a device-side finiteness check of the f32
    master-gradient view — an overflow step updates neither weights,
    nor optimizer state, nor error-feedback residuals — and the
    scaler's (scale, good_steps, skips) triple is donated through the
    program so skip bookkeeping never touches the host. The scale
    itself stays a runtime scalar in that triple; MXNet loss heads
    (SoftmaxOutput & co) generate their own gradient independent of
    the output cotangent, so the backward chain is not cotangent-
    scaled — see docs/TRAINING.md on why bf16's f32-matched exponent
    range makes overflow DETECTION, not underflow scaling, the useful
    half of the scaler here."""
    upd = _fused.build(mode)

    # analyze: ok(retrace) upd is a pure memoized function of `mode`, which is a builder parameter and part of the fit-program cache key
    def step(params, states, residuals, macc, scaler_state, sent_state,
             inputs, auxs, lr_vec, wd_vec, rescale, extra, seed):
        _note_retrace()   # trace-time host side effect only

        def f(p):
            outs, new_auxs = graph_fn({**inputs, **p}, auxs, seed, True)
            return outs, new_auxs

        if mirror:
            # MXNET_BACKWARD_DO_MIRROR: rematerialize the forward
            # (jax.checkpoint), matching executor._make_fwd_bwd
            f = jax.checkpoint(f)
        outs, vjp_fn, new_auxs = jax.vjp(f, params, has_aux=True)
        cts = [jnp.ones_like(o) for o in outs]
        (grads,) = vjp_fn(cts)

        # the f32 master-gradient view: error-feedback residuals and
        # the optimizer math both run on it, so bf16 model grads are
        # widened exactly once, before compression
        g32 = {name: grads[name].astype(jnp.float32)
               for name in param_order}

        def apply_updates(_):
            # 2-bit quantize with donated error-feedback residual; a
            # mesh-sharded batch already yielded psum-reduced
            # (replicated) grads from the vjp, so there is no separate
            # reduce stage to launch
            new_res, red = {}, {}
            for name in param_order:
                if threshold is not None:
                    red[name], new_res[name] = two_bit_quantize(
                        residuals[name], g32[name], threshold)
                else:
                    red[name] = g32[name]
            new_ps, new_ss = {}, {}
            for i, name in enumerate(param_order):
                st = _fused.unflatten(tpls[i], states[name])
                e = extra[i] if upd.n_extra else ()
                new_w, new_s = _fused.apply_one(
                    upd, params[name], red[name], st, mp_flags[i],
                    lr_vec[i], wd_vec[i], rescale, e, use_wd)
                new_ps[name] = new_w
                new_ss[name] = tuple(_fused.flatten_state(new_s)[0])
            return (new_ps, new_ss,
                    new_res if threshold is not None else residuals)

        if scaler is not None:
            finite = jnp.bool_(True)
            for name in param_order:
                finite = jnp.logical_and(
                    finite, jnp.all(jnp.isfinite(g32[name])))
            new_ps, new_ss, new_res = jax.lax.cond(
                finite, apply_updates, lambda _: (params, states, residuals),
                None)
            new_scaler = scaler.step_fn(finite, scaler_state)
        else:
            new_ps, new_ss, new_res = apply_updates(None)
            new_scaler = scaler_state

        bsum = bnum = None
        if metric_fn is not None:
            bsum, bnum = metric_fn(inputs, outs)
            macc = (macc[0] + bsum, macc[1] + bnum)

        new_sent = sent_state
        if sentinel:
            # in-launch numerics witnesses: a few reductions over
            # arrays this program already holds, carried in one donated
            # f32[8] vector — [metric_ema, metric_var, n_steps,
            # cum_nonfinite, grad_norm, zscore, residual_ema,
            # residual_drift]. Same launch, zero host syncs; the host
            # reads it only at sync boundaries (publish_sentinels).
            gnsq = jnp.float32(0.0)
            nonfin = jnp.float32(0.0)
            for name in param_order:
                g = g32[name]
                gnsq = gnsq + jnp.sum(jnp.square(g))
                nonfin = nonfin + jnp.sum(
                    (~jnp.isfinite(g)).astype(jnp.float32))
            gnorm = jnp.sqrt(gnsq)
            if bsum is not None:
                mval = (bsum / jnp.maximum(bnum, 1)).astype(jnp.float32)
            else:
                mval = gnorm    # no device metric: track the grad norm
            ema, emvar, n, cnf, rema = (sent_state[0], sent_state[1],
                                        sent_state[2], sent_state[3],
                                        sent_state[6])
            d = mval - ema
            z = jnp.where(n >= _SENT_WARMUP,
                          d * jax.lax.rsqrt(emvar + jnp.float32(1e-12)),
                          jnp.float32(0.0))
            # a non-finite sample must trip the z-score/counter, not
            # poison the running baseline forever
            ok = jnp.isfinite(mval)
            new_ema = jnp.where(ok, ema + (1.0 - _SENT_DECAY) * d, ema)
            new_var = jnp.where(
                ok, _SENT_DECAY * (emvar + (1.0 - _SENT_DECAY) * d * d),
                emvar)
            if threshold is not None:
                rnsq = jnp.float32(0.0)
                for name in param_order:
                    rnsq = rnsq + jnp.sum(jnp.square(new_res[name]))
                rnorm = jnp.sqrt(rnsq)
                drift = jnp.where(rema > 0.0,
                                  rnorm / (rema + jnp.float32(1e-30)),
                                  jnp.float32(1.0))
                new_rema = _SENT_DECAY * rema \
                    + (1.0 - _SENT_DECAY) * rnorm
            else:
                drift = jnp.float32(0.0)
                new_rema = rema
            new_sent = jnp.stack(
                [new_ema, new_var, n + 1.0, cnf + nonfin, gnorm, z,
                 new_rema, drift]).astype(jnp.float32)
        return (new_ps, new_ss, new_res, macc, new_scaler, new_sent,
                new_auxs, outs)

    # params/states/residuals/macc/scaler/auxs donate in place — except
    # under the persistent cache, where disk-loaded donated executables
    # corrupt memory (aot.store.donation_safe): the guard trades the
    # in-place update for correct zero-compile restarts.
    from ..aot.store import safe_donate_argnums as _donate
    donate = _donate((0, 1, 2, 3, 4, 5, 7))
    fn = jax.jit(step, donate_argnums=donate)
    if donate:
        _telemetry.programs.note_donation(fn, donate)
    return fn


class FusedFitStep:
    """Per-Module driver for the single-launch fit step."""

    _METRIC_UNSET = object()

    def __init__(self, module, updater, kv, threshold, mode, pmesh=None,
                 scaler=None):
        self._mod = module
        self._updater = updater
        self._kv = kv                 # None, plain local KVStore, or tpu
        self._threshold = threshold
        self._mode = mode             # optimizer._fused_fit_sig() at build
        self._scaler = scaler         # DynamicLossScaler (low-prec params)
        # multi-process tpu kvstore on an accelerator backend: the fit
        # program runs over this global 'dp' mesh — the vjp's gradient
        # reduction becomes the cross-host psum, keeping one launch and
        # zero host syncs per step on a pod (None single-process)
        self._pmesh = pmesh or None
        self._residuals = None        # name -> jnp residual (2-bit arm)
        # step-invariant caches (the whole FusedFitStep is rebuilt on
        # rebind/init_optimizer, so these live as long as they are valid)
        self._order = None            # trainable param names, arg order
        self._ukeys = None            # matching updater state keys
        self._metric_ref = FusedFitStep._METRIC_UNSET
        self._metric_fn = None
        self._msig = None
        # donated sentinel vector (f32[8], see _build_fit_program) and
        # the cumulative non-finite count already pushed to the registry
        self._sent_state = None
        self._published_nonfinite = 0.0
        self.launches = 0
        self._mem_tracker = _telemetry.StepMemoryTracker() \
            if _MEM_EVERY else None
        self._register_memory_groups()

    def _register_memory_groups(self):
        """Publish this step's donation sets to telemetry.memory so
        ``memory_snapshot()`` can attribute HBM to params / optimizer
        states / residuals / auxs (the 'one copy of training state'
        breakdown). Providers hold a weakref: a dead step contributes
        nothing, and the latest-built step wins the group names."""
        ref = weakref.ref(self)

        def provider(kind):
            def arrays():
                s = ref()
                if s is None or s._order is None:
                    return ()
                try:
                    exe = s._mod._exec_group._exec
                    if kind == "params":
                        return [exe.arg_dict[n]._data for n in s._order]
                    if kind == "auxs":
                        return list(exe._auxs_values().values())
                    if kind == "residuals":
                        return list((s._residuals or {}).values())
                    if kind == "opt_states":
                        out = []
                        for uk in (s._ukeys or ()):
                            leaves, _ = _fused.flatten_state(
                                s._updater.states.get(uk))
                            out.extend(l._data for l in leaves
                                       if hasattr(l, "_data"))
                        return out
                except Exception:
                    return ()
                return ()
            return arrays

        for kind in ("params", "opt_states", "residuals", "auxs"):
            _telemetry.memory.track_group(kind, provider(kind))

    # -- construction ---------------------------------------------------
    @staticmethod
    def build(module):
        """A FusedFitStep when ``module``'s configuration is eligible,
        else None (the fit loop then keeps the eager path)."""
        def no(reason):
            dbg = getattr(module.logger, "debug", None)
            if dbg:
                dbg("fused fit step disabled: %s", reason)
            return None

        # the env kill-switch is snapshotted into _fused_fit_enabled by
        # Module.__init__ — one source of truth for both knobs
        if not getattr(module, "_fused_fit_enabled", True):
            return no("disabled on this module")
        group = module._exec_group
        exe = group._exec
        if exe._group_devices is not None:
            return no("group2ctx-placed (model-parallel) executor")
        if module.inputs_need_grad:
            return no("inputs_need_grad")
        optimizer = module._optimizer
        sig = optimizer._fused_fit_sig()
        if sig is None:
            return no("optimizer %s has no fused signature"
                      % type(optimizer).__name__)
        if not _fused.supported(sig):
            return no("unsupported fused kind %r" % (sig[0],))
        kv = module._kvstore
        if module._update_on_kvstore:
            if not _fusable_kv(kv):
                return no("update_on_kvstore with %s" % type(kv).__name__)
            updater = kv._updater
        else:
            if kv is not None and not _fusable_kv(kv):
                return no("dist kvstore")
            updater = module._updater
        pmesh = _global_fit_mesh(kv, len(module._context))
        if pmesh is False:
            return no("uneven device counts across tpu kvstore processes")
        if not isinstance(updater, opt_mod.Updater):
            return no("custom updater")
        if updater.optimizer is not optimizer:
            return no("updater/optimizer mismatch")
        threshold = None
        comp = kv._compression if kv is not None else None
        if comp is not None:
            thr = getattr(comp, "threshold", None)
            if thr is None:
                return no("unsupported gradient compression")
            threshold = float(thr)
        low_prec = False
        for name in group.param_names:
            arr = exe.arg_dict.get(name)
            if arr is None or exe._grad_req.get(name, "null") == "null":
                continue
            if exe._grad_req[name] != "write":
                return no("grad_req %r on %s" % (exe._grad_req[name], name))
            if getattr(arr, "stype", "default") != "default" \
                    or (arr.dtype != _np.float32
                        and not _fused.is_low_precision(arr.dtype)):
                return no("non-dense-float param %s" % name)
            low_prec = low_prec or _fused.is_low_precision(arr.dtype)
        scaler = None
        if low_prec:
            # the scaler lives on the MODULE so it survives rebinds /
            # init_optimizer and round-trips through checkpoints
            scaler = getattr(module, "_loss_scaler", None)
            if scaler is None:
                scaler = _fused.DynamicLossScaler.from_config()
                module._loss_scaler = scaler   # None when scaling is off
        step = FusedFitStep(module, updater, kv, threshold, sig,
                            pmesh=pmesh, scaler=scaler)
        if not step._param_order():
            return no("no trainable parameters")
        return step

    # -- helpers --------------------------------------------------------
    def _param_order(self):
        group = self._mod._exec_group
        exe = group._exec
        return [n for n in group.param_names
                if n in exe.arg_dict
                and exe._grad_req.get(n, "null") != "null"]

    def _ukey(self, index, name):
        """Updater state key — matches what the eager path would use so
        optimizer state saved by one path loads into the other."""
        if self._mod._update_on_kvstore:
            return _updater_key(name)
        return _local_updater_key(index)

    def _place(self, group, exe, name, value):
        dst = exe.arg_dict[name]
        if self._pmesh is not None:
            # each process contributes its LOCAL batch as its rows of
            # the global batch, sharded over the cross-host 'dp' mesh
            from jax.sharding import NamedSharding, PartitionSpec as P
            # analyze: ok(hostsync) pod-path input staging: the process-local batch rows must cross the host to shard onto the global mesh
            host = value.asnumpy() if isinstance(value, NDArray) \
                else _np.asarray(value)  # analyze: ok(hostsync) iterator batches are host-resident; this is input staging, not a device readback
            # analyze: ok(hostsync) contiguity fix-up on the already-host staging copy
            host = _np.ascontiguousarray(host, dtype=dst._data.dtype)
            return jax.make_array_from_process_local_data(
                NamedSharding(self._pmesh, P("dp")), host)
        data = value._data if isinstance(value, NDArray) \
            else jnp.asarray(_np.asarray(value))  # analyze: ok(hostsync) iterator batches are host-resident; this is input staging, not a device readback
        if data.dtype != dst._data.dtype:
            data = data.astype(dst._data.dtype)
        if group._mesh is not None:
            return jax.device_put(data, group._batch_sharding())
        return exe._to_ctx(data)

    def _lift_repl(self, x):
        """Pod path: make a process-local array a replicated global
        array over the cross-host mesh. Arrays already carrying the
        target sharding (every output of the previous step) pass
        through jax.device_put as a no-op."""
        if x is None or self._pmesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(jnp.asarray(x),
                              NamedSharding(self._pmesh, P()))

    # -- residual spill/reseed (shared with the bucketed engine) --------
    def _seed_residuals(self, order, exe):
        # `order` is fixed for this FusedFitStep's lifetime, so any
        # non-None residual dict matches it; _release() forces a reseed
        if self._residuals is not None:
            return self._residuals
        kv = self._kv
        if kv is not None and kv._engine is not None:
            # flush pending buckets and spill their flat residuals back
            # to the per-(key,dev) dict before we take ownership
            kv._sync_engine()
        from .. import sharding as _sharding
        res = {}
        for n in order:
            w = exe.arg_dict[n]
            if kv is not None:
                # residuals live on the f32 master-gradient view; the
                # cast is a no-op for freshly seeded (already f32)
                # residuals and widens any pre-upgrade checkpoint state
                res[n] = kv._get_residual((n, 0), w)._data \
                    .astype(jnp.float32)
                kv._compression_residuals.pop((n, 0), None)
            else:
                res[n] = jnp.zeros(w.shape, jnp.float32)
            # f32 residuals ride their param's sharding (mp-sharded
            # params keep shard-local error feedback; device_put is an
            # identity when the placement already matches)
            res[n] = _sharding.match_param(res[n], w._data)
        self._residuals = res
        return res

    def _release(self):
        """Spill residual state back to the kvstore's per-(key,dev)
        dict so the eager path (and the bucketed engine's reseed)
        resumes with the exact accumulated error feedback."""
        if self._residuals and self._kv is not None:
            for n, r in self._residuals.items():
                self._kv._compression_residuals[(n, 0)] = NDArray(r)
        self._residuals = None

    # -- sentinel publish (sync boundaries only) ------------------------
    def publish_sentinels(self):
        """Read the donated sentinel vector and push it into the
        registry — the DynamicLossScaler.publish pattern: called ONLY
        at existing sync boundaries (Module._fit_sync, checkpoint
        capture), never per step, so sentinels cost zero host syncs."""
        st = self._sent_state
        if st is None:
            return None
        # analyze: ok(hostsync) sentinel publish rides an existing sync boundary (_fit_sync / checkpoint capture), never the per-step path
        vals = _np.asarray(st)
        from ..telemetry import sentinel as _sentinel
        gnorm = float(vals[4])
        zscore = float(vals[5])
        _sentinel.GRAD_NORM.set(gnorm)
        _sentinel.LOSS_ZSCORE.set(zscore)
        if self._threshold is not None:
            _sentinel.RESIDUAL_DRIFT.set(float(vals[7]))
        cum = float(vals[3])
        delta = int(round(cum - self._published_nonfinite))
        if delta > 0:
            self._published_nonfinite = cum
            _sentinel.NONFINITE_GRADS.inc(delta)
            from ..telemetry.flight import RECORDER
            RECORDER.note("sentinel_trip", nonfinite=delta,
                          grad_norm=gnorm, loss_zscore=zscore)
        return vals

    # -- the step -------------------------------------------------------
    def step(self, data_batch, eval_metric=None):
        """Run one single-launch training step. Returns False when this
        batch can't take the fused path (residuals are spilled first so
        the eager fallback continues exactly)."""
        mod = self._mod
        if getattr(mod, "_monitor_installed", False):
            self._release()
            return False
        # re-check the mutable bits of build-time eligibility: a swapped
        # updater (kv.set_updater after init) or a mutated optimizer
        # hyperparameter must not silently keep the stale program
        live_updater = mod._kvstore._updater if mod._update_on_kvstore \
            else mod._updater
        if live_updater is not self._updater:
            self._release()
            return False
        mode = mod._optimizer._fused_fit_sig()
        if mode is None or not _fused.supported(mode):
            self._release()
            return False
        group = mod._exec_group
        exe = group._exec
        data = getattr(data_batch, "data", None)
        labels = getattr(data_batch, "label", None) or []
        if not data or len(data) != len(group.data_names) \
                or (group.label_names
                    and len(labels) < len(group.label_names)):
            self._release()
            return False
        for v in list(data) + list(labels):
            if isinstance(v, NDArray) \
                    and getattr(v, "stype", "default") != "default":
                self._release()
                return False

        inputs = {}
        try:
            for name, v in zip(group.data_names, data):
                inputs[name] = self._place(group, exe, name, v)
            for name, v in zip(group.label_names, labels):
                inputs[name] = self._place(group, exe, name, v)
        except Exception as e:              # e.g. unshardable ragged batch
            dbg = getattr(mod.logger, "debug", None)
            if dbg:
                dbg("fused fit step falling back for this batch: %s", e)
            self._release()
            return False

        if self._order is None:
            self._order = self._param_order()
            # keys use the param's position in the FULL param_names list
            # — frozen params keep their index slots in the eager path
            # (model._update_params / Module._param_index_names), and
            # the keys must agree for lr/wd mults and state interchange
            pos = {n: i for i, n in enumerate(group.param_names)}
            self._ukeys = [self._ukey(pos[n], n) for n in self._order]
        order, ukeys = self._order, self._ukeys
        if self._kv is not None:
            # a preceding eager batch may still have overlapped pushes
            # applying weights on the kvstore pipeline thread
            # (kvstore_tpu.engine._OverlapPipeline); land them before
            # snapshotting weights/state into the donated program
            self._kv._flush_pending()
        params = {n: exe.arg_dict[n]._data for n in order}
        for n in exe._arg_names:
            if n not in inputs and n not in params:
                inputs[n] = exe.arg_dict[n]._data   # fixed/no-grad args

        updater, optimizer = self._updater, mod._optimizer
        # validate loaded states BEFORE any side effects: an abort here
        # must not have advanced update counts or created state entries
        for uk in ukeys:
            st = updater.states.get(uk)
            if st is not None:
                leaves, _ = _fused.flatten_state(st)
                if not all(isinstance(l, NDArray) for l in leaves):
                    self._release()
                    return False   # e.g. a host-side custom state blob
        states_nd, tpls, mp_flags = [], [], []
        for n, uk in zip(order, ukeys):
            if uk not in updater.states:
                updater.states[uk] = optimizer.create_state_multi_precision(
                    uk, exe.arg_dict[n])
                updater.states_synced[uk] = True
            st = updater.states[uk]
            states_nd.append(st)
            tpls.append(_fused.state_template(st))
            # multi-precision is an EXPLICIT static flag (an Adam
            # (mean, var) pair is structurally ambiguous with an
            # (inner, weight32) master tuple)
            mp_flags.append(bool(optimizer.multi_precision)
                            and _fused.is_low_precision(
                                exe.arg_dict[n].dtype))
        lr_vec, wd_vec, extra = optimizer._fused_runtime(ukeys)
        use_wd = bool(_np.any(wd_vec != 0.0))
        tpls, mp_flags = tuple(tpls), tuple(mp_flags)
        if group._mesh is not None:
            # optimizer-state leaves inherit each param's sharding, so
            # mp-sharded params carry mp-sharded moments/masters inside
            # the donated program (no resharding at the jit boundary)
            from .. import sharding as _sharding
            for n, st in zip(order, states_nd):
                w = exe.arg_dict[n]._data
                for l in _fused.flatten_state(st)[0]:
                    l._set_data(_sharding.match_param(l._data, w))
        states = {n: tuple(l._data for l in _fused.flatten_state(st)[0])
                  for n, st in zip(order, states_nd)}
        residuals = self._seed_residuals(order, exe) \
            if self._threshold is not None else {}

        if eval_metric is not self._metric_ref:
            self._metric_fn, self._msig = _metric_closure(
                eval_metric, group.label_names, mod._symbol.list_outputs())
            self._metric_ref = eval_metric
        metric_fn, msig = self._metric_fn, self._msig
        from .. import config as _config
        mirror = _config.backward_do_mirror()
        scaler = self._scaler
        if scaler is not None:
            # a checkpoint restore may have swapped the module's scaler
            # object; its step_fn is pure in trace_sig so cached
            # programs built against the old object stay valid
            scaler = getattr(mod, "_loss_scaler", None) or scaler
            self._scaler = scaler
        scaler_sig = scaler.trace_sig() if scaler is not None else None
        sent_on = _sentinel_enabled()
        cache = _compiled_cache(mod._symbol).setdefault("fit_step", {})
        # `mode` re-read above: mutating optimizer hyperparams mid-
        # training switches programs (one retrace), like the eager path
        key = (tuple(order), self._threshold, mode, tpls, mp_flags,
               use_wd, msig, mirror, scaler_sig, sent_on)
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _build_fit_program(
                _compiled_cache(mod._symbol)["graph_fn"], tuple(order),
                self._threshold, mode, tpls, mp_flags, use_wd,
                metric_fn, mirror, scaler, sentinel=sent_on)

        macc = ()
        if metric_fn is not None:
            macc = (eval_metric._dev_sum
                    if eval_metric._dev_sum is not None else jnp.float32(0.0),
                    eval_metric._dev_num
                    if eval_metric._dev_num is not None else jnp.float32(0.0))

        scaler_state = scaler.device_state() if scaler is not None else ()
        sent_state = ()
        if sent_on:
            sent_state = self._sent_state
            if sent_state is None:
                sent_state = jnp.zeros(8, jnp.float32)
        auxs = exe._auxs_values()
        if self._pmesh is not None:
            # lift every program input onto the cross-host mesh (no-op
            # for arrays the previous step already left there)
            params = {n: self._lift_repl(v) for n, v in params.items()}
            states = {n: tuple(self._lift_repl(l) for l in v)
                      for n, v in states.items()}
            residuals = {n: self._lift_repl(v)
                         for n, v in residuals.items()}
            auxs = {n: self._lift_repl(v) for n, v in auxs.items()}
            inputs = {n: (v if getattr(getattr(v, "sharding", None),
                                       "mesh", None) is self._pmesh
                          else self._lift_repl(v))
                      for n, v in inputs.items()}
            macc = tuple(self._lift_repl(m) for m in macc)
            scaler_state = tuple(self._lift_repl(s) for s in scaler_state)
            if sent_on:
                sent_state = self._lift_repl(sent_state)

        seed = exe._next_seed()
        rescale = _np.float32(optimizer.rescale_grad)
        _count_dispatch()
        track_mem = (self._mem_tracker is not None
                     and self.launches % _MEM_EVERY == 0)
        if track_mem:
            self._mem_tracker.begin()
        try:
            with exe._prof_scope("Module::fused_fit_step"), \
                    _telemetry.tracing.span("fit.fused_dispatch"):
                (new_ps, new_ss, new_res, macc, new_scaler, new_sent,
                 new_auxs, outs) = _SITE.timed(
                    fn, params, states, residuals, macc, scaler_state,
                    sent_state, inputs, auxs, lr_vec, wd_vec, rescale,
                    extra, seed)
        except Exception:
            # a runtime failure after donation consumes the donated
            # buffers — drop our residual refs so a later spill doesn't
            # resurrect deleted arrays, then surface the error (the
            # module's device state is not recoverable at this point)
            self._residuals = None
            self._sent_state = None
            raise
        if track_mem:
            self._mem_tracker.end()

        # rebind every donated buffer to its new value
        kv_store = self._kv._store \
            if (self._kv is not None and mod._update_on_kvstore) else None
        for n, st in zip(order, states_nd):
            exe.arg_dict[n]._set_data(new_ps[n])
            if kv_store is not None and n in kv_store:
                kv_store[n]._set_data(new_ps[n])
            for leaf, new_leaf in zip(_fused.flatten_state(st)[0],
                                      new_ss[n]):
                leaf._set_data(new_leaf)
        if self._threshold is not None:
            self._residuals = dict(new_res)
        if scaler is not None:
            scaler.set_device_state(new_scaler)
        self._sent_state = new_sent if sent_on else None
        exe._write_auxs(new_auxs)
        exe._outputs = [NDArray(o, exe._ctx) for o in outs]
        exe._pending_train_fwd = False
        exe._train_seed = None
        exe._train_auxs = None
        if metric_fn is not None:
            eval_metric._dev_sum, eval_metric._dev_num = macc
            eval_metric._device_consumed = True
        mod._params_dirty = True
        self.launches += 1
        return True
