"""BucketingModule: one compiled executor per bucket key, shared weights.

Behavioral parity with the reference's ``python/mxnet/module/
bucketing_module.py`` (same constructor / ``switch_bucket`` surface), built
around the TPU-natural design (SURVEY.md §7 hard part 2): the shape-keyed
jit cache means each bucket is just a ``Module`` bound against the default
bucket's parameter arrays — switching buckets swaps which compiled XLA
program runs next, never the weights.  Internally buckets are materialised
on demand by ``_materialize`` from one captured kwargs record, rather than
the reference's inline re-construction at each site.
"""
from __future__ import annotations

import logging
import warnings

from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """Drive a ``sym_gen(bucket_key) -> (symbol, data_names, label_names)``
    factory; grads/optimizer state live on the default bucket's module."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise ValueError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        # One record of Module-constructor kwargs, reused for every bucket.
        self._mod_kwargs = dict(
            logger=logger, context=context, work_load_list=work_load_list,
            fixed_param_names=fixed_param_names or [],
            state_names=state_names or [], group2ctxs=group2ctxs,
            compression_params=compression_params)
        self._reset_bind()
        self._params_dirty = False
        self._monitor = None
        self._grad_req = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._active = None
        self._active_key = None

    # -- introspection --------------------------------------------------
    @property
    def data_names(self):
        if self.binded:
            return self._active.data_names
        return self._call_sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._active.output_names
        return self._call_sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._active.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._active.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._active.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._active.symbol

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    # -- parameters -----------------------------------------------------
    def get_params(self):
        assert self.params_initialized
        self._active._params_dirty = self._params_dirty
        params = self._active.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._active.init_params(initializer=initializer,
                                 arg_params=arg_params, aux_params=aux_params,
                                 allow_missing=allow_missing,
                                 force_init=force_init,
                                 allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False")
            return
        self._active.set_params(arg_params, aux_params,
                                allow_missing=allow_missing,
                                force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    # -- binding / bucket management ------------------------------------
    def _materialize(self, bucket_key, data_shapes, label_shapes, shared):
        """Create and bind the Module for one bucket key."""
        symbol, data_names, label_names = self._call_sym_gen(bucket_key)
        mod = Module(symbol, data_names, label_names, **self._mod_kwargs)
        mod.bind(data_shapes, label_shapes, self.for_training,
                 self.inputs_need_grad, force_rebind=False,
                 shared_module=shared, grad_req=self._grad_req)
        if self._monitor is not None:
            mod.install_monitor(self._monitor)
        self._buckets[bucket_key] = mod
        return mod

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise ValueError("BucketingModule does not support shared_module")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True
        self._active = self._materialize(self._default_bucket_key,
                                         data_shapes, label_shapes, None)
        self._active_key = self._default_bucket_key

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Make ``bucket_key`` the active executor, materialising it (bound
        against the default bucket's weights) on first use."""
        assert self.binded, "call bind before switching bucket"
        mod = self._buckets.get(bucket_key)
        if mod is None:
            mod = self._materialize(bucket_key, data_shapes, label_shapes,
                                    self._buckets[self._default_bucket_key])
        self._active = mod
        self._active_key = bucket_key

    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Pre-materialise the bucket for a lookahead batch without leaving
        the current one active."""
        assert self.binded and self.params_initialized
        current = self._active_key
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self.switch_bucket(current, None, None)

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._active.init_optimizer(kvstore, optimizer, optimizer_params,
                                    force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._active:
                mod.borrow_optimizer(self._active)
        self.optimizer_initialized = True

    # -- execution (delegates to the active bucket) ---------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._active.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._active.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        self._active.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._active.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._active.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        self._active.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)
