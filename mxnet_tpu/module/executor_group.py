"""DataParallelExecutorGroup: multi-device data parallelism.

Reference parity: python/mxnet/module/executor_group.py:143. The reference
slices each batch across per-device executors (decide_slices :281) and
gathers gradients through kvstore. TPU-native (SURVEY.md §7): ONE executor
over a ``jax.sharding.Mesh`` with the batch sharded on the 'dp' axis and
parameters replicated — XLA partitions the compiled step SPMD and inserts
ICI all-reduces for the gradients, replacing per-device executors + Comm.
"""
from __future__ import annotations

import numpy as _np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..context import cpu
from ..ndarray import NDArray
from ..ndarray.ndarray import array as nd_array
from ..io.io import DataDesc
from ..parallel.mesh import data_parallel_mesh

__all__ = ["DataParallelExecutorGroup"]


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=None, fixed_param_names=None,
                 grad_req="write", state_names=None, group2ctxs=None):
        self.symbol = symbol
        self.contexts = contexts
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.state_names = state_names or []
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [d.name if isinstance(d, DataDesc) else d[0]
                           for d in data_shapes]
        self.label_names = [l.name if isinstance(l, DataDesc) else l[0]
                            for l in (label_shapes or [])]
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self._grad_req_arg = grad_req

        self._n_dev = len(contexts)
        # an explicitly selected mesh (mx.sharding.set_mesh / MXTPU_MESH)
        # takes over when it spans exactly this group's devices: the
        # batch shards over its 'dp' axis while annotated params
        # partition over 'mp' — else the implicit 1-D dp mesh as before
        from .. import sharding as _sharding
        smesh = _sharding.get_mesh()
        if smesh is not None and "dp" in smesh.axis_names \
                and smesh.devices.size == self._n_dev > 1:
            self._mesh = smesh
        else:
            self._mesh = data_parallel_mesh(contexts) \
                if self._n_dev > 1 else None

        req = {}
        for name in self.arg_names:
            if name in self.data_names:
                req[name] = "null"
            elif name in self.label_names:
                req[name] = "null"
            elif name in self.fixed_param_names or not for_training:
                req[name] = "null"
            else:
                req[name] = grad_req if isinstance(grad_req, str) \
                    else grad_req.get(name, "write")
        if inputs_need_grad:
            for name in self.data_names:
                req[name] = "write"
        shapes = {}
        for d in list(data_shapes) + list(label_shapes or []):
            name, shp = (d.name, d.shape) if isinstance(d, DataDesc) \
                else (d[0], d[1])
            shapes[name] = shp
        shared_exec = shared_group.execs[0] if shared_group else None
        # the reference takes one group2ctx dict per device (executor_
        # group.py:143 group2ctxs); with ONE sharded executor the first
        # entry is the placement map (ctx_group -> device, honored by
        # Executor via in-program jax.device_put)
        g2c = group2ctxs[0] if isinstance(group2ctxs, (list, tuple)) \
            and group2ctxs else group2ctxs
        self.execs = [symbol.simple_bind(contexts[0], req,
                                         shared_exec=shared_exec,
                                         group2ctx=g2c, **shapes)]
        self._exec = self.execs[0]
        if self._mesh is not None:
            self._install_shardings()

        # Module-facing views: param_arrays[i] is the list of per-device
        # arrays for param i — with one sharded executor that list has one
        # entry (the global array).
        self.param_arrays = [[self._exec.arg_dict[n]] for n in param_names
                             if n in self._exec.arg_dict]
        self.grad_arrays = [[self._exec.grad_dict[n]]
                            if self._exec.grad_dict.get(n) is not None else [None]
                            for n in param_names if n in self._exec.arg_dict]
        self.aux_arrays = [[self._exec.aux_dict[n]] for n in self.aux_names]

    @property
    def push_order(self):
        """param_arrays indices in backward gradient-availability order:
        arguments list in forward/topological order, so backward produces
        the last parameters' gradients first. The bucketed kvstore's
        streaming flush (kvstore_fused.enqueue) dispatches each bucket as
        soon as enough pending bytes accumulate, so enqueue order decides
        which buckets hit the device while the host is still walking the
        remaining keys (model.py _batched_push)."""
        return list(range(len(self.param_arrays)))[::-1]

    # ------------------------------------------------------------------
    def _batch_sharding(self):
        return NamedSharding(self._mesh, P("dp"))

    def _repl_sharding(self):
        return NamedSharding(self._mesh, P())

    def _param_shardings(self):
        """{param name: NamedSharding} for __sharding__-annotated vars,
        resolved against THIS group's mesh (which may be the implicit
        1-D dp mesh, where specs naming only 'mp' would fail loudly)."""
        from .. import sharding as _sharding
        axes = set(self._mesh.axis_names)
        out = {}
        for name, s in _sharding.collect_var_specs(self.symbol).items():
            arr = self._exec.arg_dict.get(name) \
                if name in self._exec.arg_dict \
                else self._exec.aux_dict.get(name)
            if arr is None:
                continue
            entries = _sharding.parse_spec(s)
            named = {a for e in entries if e is not None
                     for a in (e if isinstance(e, tuple) else (e,))}
            if not named <= axes:
                # annotations for axes this mesh doesn't carry are
                # latent (TP symbol bound on the implicit dp mesh runs
                # replicated); an explicitly selected mesh already
                # failed loudly in Executor._install_param_shardings
                continue
            out[name] = _sharding.resolve(s, arr.shape, self._mesh,
                                          what=name)
        return out

    def _install_shardings(self):
        repl = self._repl_sharding()
        bsh = self._batch_sharding()
        psh = self._param_shardings()
        for name, arr in self._exec.arg_dict.items():
            if name in self.data_names or name in self.label_names:
                sh = bsh
            else:
                sh = psh.get(name, repl)
            arr._set_data(jax.device_put(arr._data, sh))
        for name, arr in self._exec.aux_dict.items():
            arr._set_data(jax.device_put(arr._data, psh.get(name, repl)))
        for name, arr in self._exec.grad_dict.items():
            if arr is not None:
                # grads inherit their param's sharding (GSPMD's vjp of an
                # mp-sharded matmul yields mp-sharded weight grads)
                arr._set_data(jax.device_put(arr._data, psh.get(name, repl)))

    def _place_input(self, name, value):
        data = value._data if isinstance(value, NDArray) else \
            nd_array(_np.asarray(value))._data
        if self._mesh is not None:
            data = jax.device_put(data, self._batch_sharding())
        else:
            # iterator batches live on the cpu context (reference
            # contract); move them to the bind device exactly once here
            data = self._exec._to_ctx(data)
        dst = self._exec.arg_dict[name]
        if data.shape != dst.shape:
            raise MXNetError("input '%s' shape %s != bound shape %s (use "
                             "module.reshape)" % (name, data.shape, dst.shape))
        dst._set_data(data.astype(dst._data.dtype))

    # ------------------------------------------------------------------
    def load_data_batch(self, data_batch):
        data = data_batch.data
        for name, value in zip(self.data_names, data):
            self._place_input(name, value)
        if self.label_names and data_batch.label:
            for name, value in zip(self.label_names, data_batch.label):
                self._place_input(name, value)

    def forward(self, data_batch, is_train=None):
        self.load_data_batch(data_batch)
        if is_train is None:
            is_train = self.for_training
        self._exec.forward(is_train=is_train)

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to call backward")
        self._exec.backward(out_grads)

    def get_outputs(self, merge_multi_context=True, begin=0, end=None):
        outs = list(self._exec.outputs)
        if end is None:
            end = len(outs)
        outs = outs[begin:end]
        return outs if merge_multi_context else [[o] for o in outs]

    def get_input_grads(self, merge_multi_context=True):
        grads = [self._exec.grad_dict.get(n) for n in self.data_names]
        return grads if merge_multi_context else [[g] for g in grads]

    def set_params(self, arg_params, aux_params, allow_extra=False):
        self._exec.copy_params_from(arg_params, aux_params, allow_extra)
        if self._mesh is not None:
            self._install_shardings()

    def get_params(self, arg_params, aux_params):
        for name in self.param_names:
            if name in self._exec.arg_dict:
                arg_params[name] = nd_array(
                    self._exec.arg_dict[name].asnumpy(), ctx=cpu())
        for name in self.aux_names:
            aux_params[name] = nd_array(
                self._exec.aux_dict[name].asnumpy(), ctx=cpu())

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        from ..metric import consume_device_batch
        if consume_device_batch(eval_metric):
            # the fused fit step (module/fused_fit.py) already folded
            # this batch into the device accumulator — touching
            # self._exec.outputs here would only force materialization
            return
        eval_metric.update_dict(
            dict(zip(self.label_names, labels or [])),
            dict(zip(self.symbol.list_outputs(), list(self._exec.outputs))))

    def reshape(self, data_shapes, label_shapes):
        return DataParallelExecutorGroup(
            self.symbol, self.contexts, None, data_shapes, label_shapes,
            self.param_names, self.for_training, self.inputs_need_grad,
            shared_group=self, fixed_param_names=self.fixed_param_names,
            grad_req=self._grad_req_arg, state_names=self.state_names)

    def install_monitor(self, mon):
        monitor_all = getattr(mon, "monitor_all", False)
        for exe in self.execs:
            if hasattr(mon, "install"):
                # Monitor picks stream vs tapped mode (on-device stat vs
                # full-tensor second program) — don't bypass that choice
                mon.install(exe)
            else:
                # duck-typed monitor (stat_helper attr) or a bare
                # (name, NDArray) callable: full-tensor tapped mode
                cb = getattr(mon, "stat_helper", mon)
                exe.set_monitor_callback(cb, monitor_all, mode="tapped")
