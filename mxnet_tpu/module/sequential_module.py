"""SequentialModule: a pipeline of sub-modules, each feeding the next.

API parity with the reference's ``python/mxnet/module/sequential_module.py``
(``add(module, take_labels=..., auto_wiring=...)``, same META_* constants),
re-derived around an explicit ``_Stage`` record per sub-module instead of the
reference's parallel meta-dict list.  Forward threads each stage's outputs
into the next stage's inputs; backward threads input-gradients in reverse.
Each stage still compiles to its own fused XLA program, so a sequential
module is a chain of compiled steps rather than one — use plain ``Module``
on a composed symbol when you want single-program fusion.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any

from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ["SequentialModule"]


@dataclass
class _Stage:
    """One link of the chain and its wiring options."""
    module: Any
    takes_labels: bool = False
    auto_wire: bool = False


class SequentialModule(BaseModule):
    """Chain sub-modules; data flows first→last, gradients last→first."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._stages: list[_Stage] = []
        self._label_shapes = None

    # -- construction ---------------------------------------------------
    def add(self, module, **kwargs):
        """Append ``module``; keyword metas select label routing/auto-wiring."""
        allowed = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        unknown = set(kwargs) - allowed
        if unknown:
            raise ValueError(f"Unknown meta keys {sorted(unknown)}; "
                             f"allowed: {sorted(allowed)}")
        self._stages.append(_Stage(
            module=module,
            takes_labels=bool(kwargs.get(self.META_TAKE_LABELS, False)),
            auto_wire=bool(kwargs.get(self.META_AUTO_WIRING, False))))
        # Any structural edit invalidates previous binding state.
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    def _mods(self):
        return [s.module for s in self._stages]

    # -- introspection --------------------------------------------------
    @property
    def data_names(self):
        return self._stages[0].module.data_names if self._stages else []

    @property
    def output_names(self):
        return self._stages[-1].module.output_names if self._stages else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._stages[0].module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._stages[-1].module.output_shapes

    # -- parameters -----------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        args, auxs = {}, {}
        for m in self._mods():
            a, x = m.get_params()
            args.update(a)
            auxs.update(x)
        return args, auxs

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for m in self._mods():
            # A name owned by stage j is "extra" from stage i's point of
            # view, so per-stage allow_extra must be True; cross-stage
            # unknown names are checked once below.
            m.init_params(initializer=initializer, arg_params=arg_params,
                          aux_params=aux_params, allow_missing=allow_missing,
                          force_init=force_init, allow_extra=True)
        if not allow_extra:
            self._reject_unclaimed(arg_params, aux_params)
        self.params_initialized = True

    def _reject_unclaimed(self, arg_params, aux_params):
        """Raise if a provided param name belongs to no stage at all."""
        if not (arg_params or aux_params):
            return
        claimed = set()
        for m in self._mods():
            claimed.update(m._arg_params or {})
            claimed.update(m._aux_params or {})
        orphans = sorted(n for src in (arg_params, aux_params)
                         for n in (src or {}) if n not in claimed)
        if orphans:
            from ..base import MXNetError
            raise MXNetError(
                f"init_params got parameter(s) {orphans} unknown to every "
                f"sub-module (pass allow_extra=True to ignore)")

    # -- binding --------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise ValueError("SequentialModule does not support shared_module")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        feed = data_shapes
        label_used = False
        for idx, stage in enumerate(self._stages):
            if stage.auto_wire:
                feed = self._rewire(stage.module.data_names, feed)
            stage.module.bind(
                data_shapes=feed,
                label_shapes=label_shapes if stage.takes_labels else None,
                for_training=for_training,
                # interior stages need input grads to continue backprop even
                # when the caller doesn't ask for grads w.r.t. the data
                inputs_need_grad=inputs_need_grad or (for_training and idx > 0),
                force_rebind=force_rebind, shared_module=None,
                grad_req=grad_req)
            label_used = label_used or stage.takes_labels
            feed = stage.module.output_shapes
        if not label_used:
            self._label_shapes = None
        self.binded = True

    @staticmethod
    def _rewire(names, shapes):
        """Rename upstream output descs to this stage's declared input names."""
        if len(names) != len(shapes):
            raise ValueError(
                f"auto_wiring: stage declares {len(names)} inputs but "
                f"upstream produces {len(shapes)} outputs")
        return [(name, tuple(desc)[1]) for name, desc in zip(names, shapes)]

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for m in self._mods():
            m.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                             optimizer_params=optimizer_params,
                             force_init=force_init)
        self.optimizer_initialized = True

    # -- execution ------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io.io import DataBatch
        # Work on a shallow copy: we mutate .data as activations flow through.
        flowing = DataBatch(data=data_batch.data, label=data_batch.label,
                            pad=data_batch.pad, index=data_batch.index)
        last = len(self._stages) - 1
        for idx, stage in enumerate(self._stages):
            stage.module.forward(flowing, is_train=is_train)
            if idx == last:
                break
            flowing.data = stage.module.get_outputs()
            if hasattr(flowing, "provide_data"):
                flowing.provide_data = [
                    (getattr(desc, "name", desc[0]), out.shape)
                    for desc, out in zip(stage.module.output_shapes,
                                         flowing.data)]

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for idx in range(len(self._stages) - 1, -1, -1):
            self._stages[idx].module.backward(out_grads=out_grads)
            if idx:
                out_grads = self._stages[idx].module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for m in self._mods():
            m.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._stages[-1].module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._stages[0].module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for stage in self._stages:
            if stage.takes_labels:
                stage.module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        for m in self._mods():
            m.install_monitor(mon)
