"""Module: symbol + executor-group + optimizer intermediate API.

Reference parity: python/mxnet/module/module.py (bind :364, init_params
:270, init_optimizer :465, forward :570, backward :600, update :643).
"""
from __future__ import annotations

import logging
import warnings

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform, InitDesc
from .. import optimizer as opt
from ..model import (_create_kvstore, _initialize_kvstore,
                     _update_params_on_kvstore, _update_params,
                     load_checkpoint, save_checkpoint)
from ..io.io import DataDesc
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._group2ctxs = group2ctxs

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """(reference module.py:165)"""
        self._symbol.save("%s-symbol.json" % prefix)
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, None, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        # infer from the bound input shapes — must work before any
        # forward has run (SequentialModule wires layers at bind time)
        known = {d.name: d.shape for d in self._data_shapes}
        if self._label_shapes:
            known.update({l.name: l.shape for l in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape_partial(**known)
        return list(zip(self._output_names,
                        [tuple(s) if s is not None else None
                         for s in out_shapes]))

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded or self.params_initialized
        if self.binded and self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False")
            return
        assert self.binded, "call bind before initializing the parameters"

        if self._arg_params is None:
            self._arg_params = {
                name: arr[0].copyto(cpu())
                for name, arr in zip(
                    [n for n in self._param_names
                     if n in self._exec_group._exec.arg_dict],
                    self._exec_group.param_arrays)}
        if self._aux_params is None:
            self._aux_params = {
                name: arr[0].copyto(cpu())
                for name, arr in zip(self._aux_names,
                                     self._exec_group.aux_arrays)}

        attrs = self._symbol.attr_dict()

        if not allow_extra:
            # reference module.py set_params: unknown names are an error
            # unless allow_extra_params is set
            extra = [n for n in (arg_params or {})
                     if n not in self._arg_params]
            extra += [n for n in (aux_params or {})
                      if n not in self._aux_params]
            if extra:
                raise MXNetError(
                    "set_params/init_params got extra parameter(s) %s "
                    "(pass allow_extra=True to ignore)" % sorted(extra))

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    if cache_arr.shape != arr.shape:
                        raise MXNetError("shape mismatch for %s: %s vs %s"
                                         % (name, cache_arr.shape, arr.shape))
                    cache_arr.copyto(arr)
            else:
                if not allow_missing:
                    raise RuntimeError("%s is not presented" % name)
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name)), arr)

        for name, arr in sorted(self._arg_params.items()):
            if arg_params is not None:
                _impl(name, arr, arg_params)
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), arr)
        for name, arr in sorted(self._aux_params.items()):
            if aux_params is not None:
                _impl(name, arr, aux_params)
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), arr)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=True)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False")
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = DataDesc.get_list(
            [tuple(d) if not isinstance(d, DataDesc) else d
             for d in data_shapes])
        self._label_shapes = DataDesc.get_list(
            [tuple(l) if not isinstance(l, DataDesc) else l
             for l in label_shapes]) if label_shapes else None

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names,
            group2ctxs=self._group2ctxs)
        self._total_exec_bytes = 0
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)
        if shared_module is not None and shared_module.optimizer_initialized:
            # a bucket created mid-training adopts the live optimizer
            # (reference module.py:455)
            self.borrow_optimizer(shared_module)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = DataDesc.get_list(
            [tuple(d) if not isinstance(d, DataDesc) else d
             for d in data_shapes])
        self._label_shapes = DataDesc.get_list(
            [tuple(l) if not isinstance(l, DataDesc) else l
             for l in label_shapes]) if label_shapes else None
        self._exec_group = self._exec_group.reshape(self._data_shapes,
                                                    self._label_shapes)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        first = self._exec_group.data_shapes[0]
        batch_size = first.shape[0] if isinstance(first, DataDesc) \
            else first[1][0]
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update(
                    {i * len(self._context) + k: n
                     for i, n in enumerate(self._exec_group.param_names)})
        # param_names for the exec group = Module's param names present
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self._symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size/"
                    "num_workers (%s vs. %s). Is this intended?"
                    % (optimizer.rescale_grad, rescale_grad))

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._exec_group.param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        curr_data_shapes = tuple(i.shape for i in self._data_shapes)
        if isinstance(data_batch, list):
            new_data_shapes = tuple(b.data[0].shape for b in data_batch)
        else:
            new_data_shapes = tuple(i.shape for i in data_batch.data)
        if curr_data_shapes != new_data_shapes:
            if hasattr(data_batch, "provide_data") and data_batch.provide_data:
                new_dshape = data_batch.provide_data
            else:
                new_dshape = [DataDesc(i.name, shape, i.dtype, i.layout)
                              for i, shape in
                              zip(self._data_shapes, new_data_shapes)]
            if hasattr(data_batch, "provide_label") and data_batch.provide_label:
                new_lshape = data_batch.provide_label
            elif hasattr(data_batch, "label") and data_batch.label:
                if self._label_shapes:
                    new_lshape = [DataDesc(i.name, j.shape, i.dtype,
                                           i.layout)
                                  for i, j in
                                  zip(self._label_shapes, data_batch.label)]
                else:
                    # a previous unlabeled batch dropped the label
                    # shapes; rebuild them from the declared label names
                    new_lshape = [DataDesc(name, j.shape)
                                  for name, j in zip(self._label_names,
                                                     data_batch.label)]
            else:
                new_lshape = None
            self.reshape(new_dshape, new_lshape)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """(reference module.py:643) push grads / run updater."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore,
                                      self._exec_group.param_names)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=1,
                           kvstore=self._kvstore,
                           param_names=self._exec_group.param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            for param_name, param_val in sorted(self._arg_params.items()):
                self._kvstore.pull(param_name, param_val)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    @property
    def _param_names_bound(self):
        return self._exec_group.param_names
