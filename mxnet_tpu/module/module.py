"""Module: symbol + executor-group + optimizer, the mid-level training API.

API parity: python/mxnet/module/module.py (bind :364, init_params :270,
init_optimizer :465, forward :570, update :643) — same surface, re-derived
implementation.  The executor group compiles forward(+backward) into one
fused XLA program per shape signature; ``forward`` transparently re-binds
when a batch arrives with a new shape (the compiled-program cache makes
that cheap after the first time).
"""
from __future__ import annotations

import logging
import os
import warnings

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform, InitDesc
from .. import optimizer as opt
from ..model import (_create_kvstore, _initialize_kvstore,
                     _update_params_on_kvstore, _update_params,
                     load_checkpoint, save_checkpoint)
from ..io.io import DataDesc
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


def _as_descs(shapes):
    """Normalise a list of (name, shape) / DataDesc into DataDesc records;
    None/empty passes through as None."""
    if not shapes:
        return None
    return DataDesc.get_list(
        [d if isinstance(d, DataDesc) else tuple(d) for d in shapes])


class Module(BaseModule):
    """Bind a Symbol over contexts and drive fused train/eval steps."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        self._context = [context] if isinstance(context, Context) else context
        self._work_load_list = work_load_list
        self._group2ctxs = group2ctxs
        self._symbol = symbol
        self._compression_params = compression_params

        names = {"data": list(data_names or []),
                 "label": list(label_names or []),
                 "state": list(state_names or []),
                 "fixed_param": list(fixed_param_names or [])}
        for kind, lst in names.items():
            _check_input_names(symbol, lst, kind, throw=kind != "label")
        self._data_names = names["data"]
        self._label_names = names["label"]
        self._state_names = names["state"]
        self._fixed_param_names = names["fixed_param"]

        non_params = set(self._data_names + self._label_names
                         + self._state_names)
        self._param_names = [a for a in symbol.list_arguments()
                             if a not in non_params]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        # single-launch fit step (module/fused_fit.py, docs/TRAINING.md):
        # built lazily on the first fit_step after init_optimizer;
        # MXNET_FIT_FUSED=0 keeps every step on the eager path
        self._fused_fit = None
        self._fused_fit_tried = False
        self._fused_fit_enabled = os.environ.get(
            "MXNET_FIT_FUSED", "1") != "0"
        self._monitor_installed = False

    # -- checkpointing --------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Rebuild a Module from ``prefix-symbol.json`` + params at epoch."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params, mod._aux_params = args, auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Write symbol/params (and optionally optimizer state) in the
        reference's file layout."""
        self._symbol.save(f"{prefix}-symbol.json")
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, None, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # -- introspection --------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        # Derived by shape inference from the bound inputs so it works
        # before any forward has run (SequentialModule wires at bind time).
        known = {d.name: d.shape for d in self._data_shapes}
        for l in self._label_shapes or []:
            known[l.name] = l.shape
        _, out_shapes, _ = self._symbol.infer_shape_partial(**known)
        return [(name, tuple(s) if s is not None else None)
                for name, s in zip(self._output_names, out_shapes)]

    @property
    def _param_names_bound(self):
        return self._exec_group.param_names

    # -- parameters -----------------------------------------------------
    def get_params(self):
        assert self.binded or self.params_initialized
        if self.binded and self._params_dirty:
            self._sync_params_from_devices()
        return self._arg_params, self._aux_params

    def _host_param_caches(self):
        """Materialise host-side copies of device params on first touch."""
        if self._arg_params is None:
            live = self._exec_group._exec.arg_dict
            bound_names = [n for n in self._param_names if n in live]
            self._arg_params = {
                name: arrs[0].copyto(cpu())
                for name, arrs in zip(bound_names,
                                      self._exec_group.param_arrays)}
        if self._aux_params is None:
            self._aux_params = {
                name: arrs[0].copyto(cpu())
                for name, arrs in zip(self._aux_names,
                                      self._exec_group.aux_arrays)}

    def _reject_extra(self, arg_params, aux_params):
        orphans = [n for n in (arg_params or {}) if n not in self._arg_params]
        orphans += [n for n in (aux_params or {}) if n not in self._aux_params]
        if orphans:
            raise MXNetError(
                f"set_params/init_params got extra parameter(s) "
                f"{sorted(orphans)} (pass allow_extra=True to ignore)")

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False")
            return
        assert self.binded, "call bind before initializing the parameters"
        self._host_param_caches()
        attrs = self._symbol.attr_dict()
        if not allow_extra:
            self._reject_extra(arg_params, aux_params)

        def fill(name, target, source):
            """Resolve one parameter: copy from `source` if present, else
            fall back to missing-policy / initializer."""
            if source is not None and name in source:
                given = source[name]
                if given is not target:
                    if given.shape != target.shape:
                        raise MXNetError(
                            f"shape mismatch for {name}: {given.shape} vs "
                            f"{target.shape}")
                    given.copyto(target)
                return
            if source is not None and not allow_missing:
                raise RuntimeError(f"{name} is not presented")
            if initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), target)

        for name, target in sorted(self._arg_params.items()):
            fill(name, target, arg_params)
        for name, target in sorted(self._aux_params.items()):
            fill(name, target, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=True)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False")
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # -- binding --------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if not for_training and inputs_need_grad:
            raise ValueError("inputs_need_grad requires for_training")

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        self.binded = True

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names,
            group2ctxs=self._group2ctxs)
        self._total_exec_bytes = 0
        if shared_module is not None:
            # share host caches and (if live) the optimizer with the donor
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            if shared_module.optimizer_initialized:
                self.borrow_optimizer(shared_module)
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        # a re-bind may change grad_req / inputs_need_grad — fused-fit
        # eligibility must be re-evaluated against the new executor
        self._fused_fit = None
        self._fused_fit_tried = False

    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind to new input shapes, reusing weights (and the compiled
        program cache keyed by shape)."""
        assert self.binded
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        self._exec_group = self._exec_group.reshape(self._data_shapes,
                                                    self._label_shapes)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)

    # -- optimizer ------------------------------------------------------
    def _effective_batch_size(self, kvstore):
        first = self._exec_group.data_shapes[0]
        batch = first.shape[0] if isinstance(first, DataDesc) \
            else first[1][0]
        if kvstore and (("dist" in kvstore.type and "_sync" in kvstore.type)
                        or kvstore.type.startswith("tpu")
                        or kvstore.type == "nccl"):
            batch *= kvstore.num_workers
        return batch

    def _param_index_names(self, update_on_kvstore):
        """Index→name map handed to the optimizer (per-device interleaved
        when updates run on workers, matching the reference's updater
        keying)."""
        names = self._exec_group.param_names
        if update_on_kvstore:
            return dict(enumerate(names))
        n_dev = len(self._context)
        return {i * n_dev + k: n
                for i, n in enumerate(names) for k in range(n_dev)}

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        rescale_grad = 1.0 / self._effective_batch_size(kvstore)

        if isinstance(optimizer, str):
            config = dict(optimizer_params)
            config.setdefault("rescale_grad", rescale_grad)
            optimizer = opt.create(
                optimizer, sym=self._symbol,
                param_idx2name=self._param_index_names(update_on_kvstore),
                **config)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    f"Optimizer created manually outside Module but "
                    f"rescale_grad is not normalized to 1.0/batch_size/"
                    f"num_workers ({optimizer.rescale_grad} vs. "
                    f"{rescale_grad}). Is this intended?")

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._exec_group.param_names,
                                update_on_kvstore=update_on_kvstore)
            if self._exec_group._mesh is not None:
                # the kvstore init/pull round-trip re-wrote the param
                # arrays with single-device copies; restore the bind-time
                # GSPMD placement (mp-sharded params must START sharded,
                # not converge to it after the first donated step)
                self._exec_group._install_shardings()
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        self._fused_fit = None          # re-evaluate fused-fit eligibility
        self._fused_fit_tried = False

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """Adopt a live optimizer/kvstore/updater from another module (the
        bucketing path)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True
        self._fused_fit = None
        self._fused_fit_tried = False

    # -- execution ------------------------------------------------------
    def _batch_descs(self, data_batch, new_shapes):
        """Build (data_descs, label_descs) for a batch whose shapes differ
        from the bound ones."""
        if getattr(data_batch, "provide_data", None):
            d_descs = data_batch.provide_data
        else:
            d_descs = [DataDesc(d.name, shape, d.dtype, d.layout)
                       for d, shape in zip(self._data_shapes, new_shapes)]
        labels = getattr(data_batch, "label", None)
        if getattr(data_batch, "provide_label", None):
            l_descs = data_batch.provide_label
        elif labels:
            if self._label_shapes:
                l_descs = [DataDesc(l.name, arr.shape, l.dtype, l.layout)
                           for l, arr in zip(self._label_shapes, labels)]
            else:
                # a previous unlabeled batch dropped the label shapes;
                # rebuild them from the declared label names
                l_descs = [DataDesc(name, arr.shape)
                           for name, arr in zip(self._label_names, labels)]
        else:
            l_descs = None
        return d_descs, l_descs

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bound = tuple(d.shape for d in self._data_shapes)
        arriving = tuple(b.data[0].shape for b in data_batch) \
            if isinstance(data_batch, list) \
            else tuple(a.shape for a in data_batch.data)
        if bound != arriving:
            self.reshape(*self._batch_descs(data_batch, arriving))
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def aot_warm(self, manifest=None):
        """mx.aot.warm hook (docs/AOT.md): dispatch the bound forward
        (+ backward when bound for training) once on zeros, so a
        restarted trainer pays trace + persistent-cache disk-load
        before its first real batch rather than during it.  Touches
        gradients only — parameters and optimizer state are untouched
        (no ``update``).  The fused fit step keys on live optimizer
        state and compiles lazily on the first ``fit_step``; with
        ``MXNET_COMPILE_CACHE_DIR`` set that compile is also a
        disk-load.  Returns the number of programs dispatched."""
        assert self.binded and self.params_initialized
        from ..io import DataBatch
        from ..ndarray import zeros as _nd_zeros
        from ..telemetry import programs as _programs
        group = self._exec_group

        def dummy(descs):
            return [_nd_zeros(tuple(d.shape if hasattr(d, "shape")
                                    else d[1])) for d in descs]

        batch = DataBatch(data=dummy(group.data_shapes),
                          label=(dummy(group.label_shapes)
                                 if group.label_shapes else None))
        with _programs.warming():
            self.forward(batch, is_train=group.for_training)
            if group.for_training:
                self.backward()
        return 1

    def fit_step(self, data_batch, eval_metric=None):
        """One training step. Eligible configurations (docs/TRAINING.md)
        run forward+backward+compress+reduce+update — plus device-side
        metric accumulation when ``eval_metric.device_fn()`` exists — as
        ONE donated compiled program (module/fused_fit.py) and return
        True; everything else falls back to the eager fwd_bwd + kvstore
        pair."""
        fused = self._get_fused_fit()
        if fused is not None and fused.step(data_batch, eval_metric):
            return True
        return super().fit_step(data_batch, eval_metric)

    def _get_fused_fit(self):
        if not self._fused_fit_tried:
            self._fused_fit_tried = True
            if self.binded and self.params_initialized \
                    and self.optimizer_initialized:
                from .fused_fit import FusedFitStep
                self._fused_fit = FusedFitStep.build(self)
        return self._fused_fit

    def _fit_sync(self):
        """Bounded async depth (MXNET_FIT_SYNC_EVERY): block until the
        last dispatched step's parameters are materialized. Must wait on
        a TRAINABLE parameter — data/label buffers and frozen params are
        plain program inputs, always ready."""
        import jax
        exe = self._exec_group._exec
        for name in self._exec_group.param_names:
            arr = exe.arg_dict.get(name)
            if arr is not None and exe._grad_req.get(name, "null") != "null":
                jax.block_until_ready(arr._data)
                break
        scaler = getattr(self, "_loss_scaler", None)
        if scaler is not None:
            # already a sync boundary: refresh the loss_scale gauge and
            # overflow-skip counter from the device triple
            scaler.publish()
        if self._fused_fit is not None:
            # same boundary: fold the in-launch numerics sentinels
            # (grad norm, non-finite count, z-score, residual drift)
            # into the registry
            self._fused_fit.publish_sentinels()
        kv = self._kvstore
        if kv is not None and getattr(kv, "_engine", None) is not None:
            # the bucketed kvstore engine carries its own non-finite
            # witness scalar; same boundary, same dedup semantics
            kv._engine.publish_sentinels()

    def update(self):
        """Apply one optimizer step (kvstore push/pull or local updater)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        if self._fused_fit is not None:
            # an eager update between fused steps must see the exact
            # accumulated error-feedback residuals — spill them back
            self._fused_fit._release()
        self._params_dirty = True
        group = self._exec_group
        if self._update_on_kvstore:
            _update_params_on_kvstore(group.param_arrays, group.grad_arrays,
                                      self._kvstore, group.param_names,
                                      push_order=group.push_order)
        else:
            _update_params(group.param_arrays, group.grad_arrays,
                           updater=self._updater, num_device=1,
                           kvstore=self._kvstore,
                           param_names=group.param_names,
                           push_order=group.push_order)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            for name, value in sorted(self._arg_params.items()):
                self._kvstore.pull(name, value)
        self._params_dirty = False

    # -- optimizer state persistence ------------------------------------
    def _live_updater(self):
        """The updater actually applying updates right now (kvstore's
        when update_on_kvstore, else the worker-local one)."""
        return self._kvstore._updater if self._update_on_kvstore \
            else self._updater

    def _opt_state_key_maps(self):
        """(name→live updater key, any-scheme→live key) maps.

        Two key schemes exist (docs/TRAINING.md): kvstore updaters key
        state by param NAME (kvstore._updater_key), local updaters by
        interleaved index (model._local_updater_key) — both shared with
        the fused fit step since PR 3. Checkpoints persist states under
        canonical param names; the alias map lets a states file written
        under EITHER scheme load into the live one, so a checkpoint
        taken with one kvstore config resumes under the other instead
        of silently dropping all momentum."""
        from ..kvstore import _updater_key
        from ..model import _local_updater_key
        names = self._exec_group.param_names
        if self._update_on_kvstore:
            name_to_live = {n: _updater_key(n) for n in names}
        else:
            name_to_live = {n: _local_updater_key(i)
                            for i, n in enumerate(names)}
        alias = {}
        for i, n in enumerate(names):
            alias[_updater_key(n)] = name_to_live[n]
            alias[_local_updater_key(i)] = name_to_live[n]
        return name_to_live, alias

    def _states_use_kvstore_file(self):
        """True when state persistence must stay delegated to the
        kvstore (dist stores keep server-side optimizer state; local
        and tpu stores hold process-local/replicated state that the
        canonical name-key translation below may rewrite)."""
        return self._update_on_kvstore \
            and not getattr(self._kvstore, "_captures_local_state", False)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._states_use_kvstore_file():
            self._kvstore.save_optimizer_states(fname)
            return
        from ..optimizer import Updater
        if getattr(self._kvstore, "_captures_local_state", False):
            self._kvstore._flush_pending()   # pending buckets touch state
        updater = self._live_updater()
        if not isinstance(updater, Updater):
            with open(fname, "wb") as fout:   # custom updater: raw dump
                fout.write(updater.get_states())
            return
        import pickle
        name_to_live, _ = self._opt_state_key_maps()
        live_to_name = {lk: n for n, lk in name_to_live.items()}
        states = {live_to_name.get(k, k): v
                  for k, v in updater.states.items()}
        with open(fname, "wb") as fout:
            fout.write(pickle.dumps(states))

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._states_use_kvstore_file():
            self._kvstore.load_optimizer_states(fname)
            return
        from ..optimizer import Updater
        if getattr(self._kvstore, "_captures_local_state", False):
            self._kvstore._flush_pending()   # pending buckets touch state
        updater = self._live_updater()
        with open(fname, "rb") as f:
            blob = f.read()
        if not isinstance(updater, Updater):
            updater.set_states(blob)
            return
        import pickle
        data = pickle.loads(blob)
        _, alias = self._opt_state_key_maps()
        if isinstance(data, tuple) and len(data) == 2:
            # dump_optimizer=True form: (states, optimizer) — adopt the
            # optimizer too, then translate the keys in place
            updater.set_states(blob)
            updater.states = {alias.get(k, k): v
                              for k, v in updater.states.items()}
            updater.states_synced = {k: False for k in updater.states}
            # keep the module's optimizer handle pointing at the LIVE
            # (unpickled) one — lr/schedule mutations must hit it
            self._optimizer = updater.optimizer
        else:
            updater.set_states({alias.get(k, k): v
                                for k, v in data.items()})

    def install_monitor(self, mon):
        assert self.binded
        # monitor taps run through the executor programs; the fused fit
        # step routes every batch back to the eager path while installed
        self._monitor_installed = True
        self._exec_group.install_monitor(mon)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass
