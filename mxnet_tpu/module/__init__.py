"""Module API (reference parity: python/mxnet/module/)."""
from . import fused_fit
from .base_module import BaseModule
from .module import Module
from .executor_group import DataParallelExecutorGroup
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule

__all__ = ["BaseModule", "Module", "DataParallelExecutorGroup",
           "BucketingModule", "SequentialModule"]
