"""Background checkpoint writer: the training loop never waits on IO.

``AsyncCheckpointWriter`` owns a daemon thread draining a job queue;
each job serializes an already-host-materialized snapshot (see
``snapshot.capture``) and publishes it through the crash-safe manifest
protocol. Transient IO errors retry with exponential backoff; a job
that exhausts its retries is logged and counted
(``checkpoint_failures``) without killing training. After every commit
the writer applies keep-N rotation and notes the event in the flight
recorder. An atexit hook drains the queue so a normally-exiting job
never loses its tail checkpoint.
"""
from __future__ import annotations

import atexit
import logging
import queue
import threading
import time

from . import manifest as _mf
from . import snapshot as _snap
from .. import telemetry as _telemetry

__all__ = ["AsyncCheckpointWriter", "BLOCK_MS", "SAVE_MS", "BYTES",
           "QUEUE_DEPTH", "SAVES", "FAILURES"]

BLOCK_MS = _telemetry.REGISTRY.histogram(
    "checkpoint_block_ms",
    "training-thread blocking time per checkpoint (device->host snapshot "
    "+ enqueue; the async path's only cost)", unit="ms")
SAVE_MS = _telemetry.REGISTRY.histogram(
    "checkpoint_save_ms",
    "wall time to serialize + atomically publish one checkpoint "
    "(writer thread for async saves)", unit="ms")
BYTES = _telemetry.REGISTRY.counter(
    "checkpoint_bytes", "cumulative bytes committed to checkpoints",
    unit="bytes")
QUEUE_DEPTH = _telemetry.REGISTRY.gauge(
    "checkpoint_queue_depth", "snapshots waiting in the async writer queue")
SAVES = _telemetry.REGISTRY.counter(
    "checkpoint_saves", "checkpoints committed (manifest published)")
FAILURES = _telemetry.REGISTRY.counter(
    "checkpoint_failures", "checkpoint writes abandoned after retries")


def write_with_retry(state, prefix, tag, retries=3, backoff=0.05,
                     logger=None, keep=0):
    """Serialize+publish one checkpoint with retry-with-backoff on
    OSError (transient NFS/GCS-fuse blips), then keep-N rotation.
    Returns the manifest; raises after the final attempt fails."""
    log = logger or logging
    t0 = time.perf_counter()
    attempt = 0
    while True:
        try:
            man = _snap.write_checkpoint(state, prefix, tag)
            break
        except OSError as e:
            attempt += 1
            if attempt > retries:
                FAILURES.inc()
                raise
            delay = backoff * (2 ** (attempt - 1))
            log.warning("checkpoint %s tag %s: write failed (%s), "
                        "retry %d/%d in %.2fs", prefix, tag, e,
                        attempt, retries, delay)
            time.sleep(delay)
    SAVE_MS.observe((time.perf_counter() - t0) * 1e3)
    SAVES.inc()
    BYTES.inc(int(man.get("total_bytes", 0)))
    _telemetry.RECORDER.note("checkpoint_save", tag=int(tag))
    if keep and keep > 0:
        for old in _mf.list_tags(prefix)[:-keep]:
            _mf.delete_checkpoint(prefix, old)
    return man


class AsyncCheckpointWriter:
    """One daemon writer thread + bounded-latency drain support."""

    def __init__(self, retries=3, backoff=0.05, logger=None,
                 max_pending=4):
        self.retries = retries
        self.backoff = backoff
        self.logger = logger or logging
        # bounded: each queued job holds a full host copy of the
        # training state, so a writer slower than the save cadence must
        # apply backpressure (submit blocks) instead of growing RSS by
        # one model per outstanding snapshot until OOM
        self._q = queue.Queue(maxsize=max(int(max_pending), 1))
        self._thread = None
        self._lock = threading.Lock()
        self._closed = False

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="mx-checkpoint-writer",
                    daemon=True)
                self._thread.start()
                atexit.register(self.drain, 60.0)

    def submit(self, state, prefix, tag, keep=0):
        """Enqueue one snapshot for background commit. Non-blocking
        until ``max_pending`` snapshots are in flight; beyond that the
        put blocks — backpressure, not unbounded host memory."""
        if self._closed:
            raise RuntimeError("checkpoint writer is closed")
        self._ensure_thread()
        if self._q.full():
            self.logger.warning(
                "checkpoint writer saturated (%d pending) — save cadence "
                "outruns storage; blocking until a slot frees",
                self._q.qsize())
        self._q.put((state, prefix, tag, keep))
        QUEUE_DEPTH.set(self._q.qsize())

    def _run(self):
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                state, prefix, tag, keep = job
                try:
                    write_with_retry(state, prefix, tag,
                                     retries=self.retries,
                                     backoff=self.backoff,
                                     logger=self.logger, keep=keep)
                except Exception:
                    # already counted by write_with_retry where it
                    # applies; never kill the writer loop
                    self.logger.exception(
                        "checkpoint %s tag %s: abandoned after %d "
                        "retries", prefix, tag, self.retries)
            finally:
                self._q.task_done()
                QUEUE_DEPTH.set(self._q.qsize())

    @property
    def pending(self):
        return self._q.unfinished_tasks

    def drain(self, timeout=None):
        """Block until every submitted checkpoint has committed (or
        ``timeout`` seconds elapsed). Returns True when fully drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if self._thread is None or not self._thread.is_alive():
                return self._q.unfinished_tasks == 0
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True

    def close(self, timeout=None):
        """Drain, then stop the writer thread. Idempotent, and bounded
        by ``timeout`` even when storage is wedged: if the queue never
        drained, the stop sentinel is only best-effort enqueued (the
        thread is a daemon — it cannot hold up process exit)."""
        if self._closed:
            return
        self._closed = True
        ok = self.drain(timeout)
        if self._thread is not None and self._thread.is_alive():
            try:
                self._q.put_nowait(None)
            except queue.Full:
                pass
            self._thread.join(timeout)
        return ok
