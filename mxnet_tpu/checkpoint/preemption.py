"""Preemption handling: SIGTERM → emergency save + graceful drain.

TPU pods get preempted with a short grace window (SIGTERM first,
SIGKILL later). ``PreemptionHandler`` turns the first signal into a
sticky flag that the fit loop polls at each step boundary
(``CheckpointManager.tick``): the loop then takes one SYNCHRONOUS
emergency checkpoint, drains the async writer, and returns from
``fit`` cleanly instead of dying mid-write. Python delivers signal
handlers on the main thread between bytecodes, so a training loop on
the main thread observes the flag within one step.

The handler chains to any previously-installed *callable* handler and
restores the original disposition on :meth:`uninstall` (driven by
``CheckpointManager.close``).
"""
from __future__ import annotations

import logging
import signal
import threading

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """Sticky signal flag with install/uninstall and chaining."""

    def __init__(self, signals=(signal.SIGTERM,), logger=None):
        self.signals = tuple(signals)
        self.logger = logger or logging
        self._event = threading.Event()
        self._previous = {}
        self._installed = False
        self._lock = threading.Lock()

    @property
    def triggered(self):
        return self._event.is_set()

    def trigger(self):
        """Mark preemption requested (also callable directly, e.g. from
        a cloud metadata watcher thread)."""
        self._event.set()

    def clear(self):
        self._event.clear()

    def _handle(self, signum, frame):
        # NO logging here: a signal handler re-entering the logging
        # module's lock (held by the interrupted main thread) would
        # self-deadlock the very path that must save state. The flag is
        # acted on — and logged — at the next step boundary
        # (CheckpointManager.emergency_save).
        self._event.set()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self):
        """Install on the configured signals. Safe to call from a
        non-main thread: installation is skipped with a warning
        (``signal.signal`` only works on the main thread) and the
        handler can still be driven via :meth:`trigger`."""
        with self._lock:
            if self._installed:
                return self
            try:
                for sig in self.signals:
                    self._previous[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # roll back any handlers already swapped in — a partial
                # install must not leave an unrecoverable disposition
                for sig, prev in self._previous.items():
                    try:
                        signal.signal(sig, prev if prev is not None
                                      else signal.SIG_DFL)
                    except (ValueError, TypeError):
                        pass
                self._previous.clear()
                self.logger.warning(
                    "checkpoint: cannot install signal handlers off the "
                    "main thread; preemption flag remains manual")
                return self
            self._installed = True
        return self

    def uninstall(self):
        """Restore the original handlers (only those still ours)."""
        with self._lock:
            if not self._installed:
                return
            for sig, prev in self._previous.items():
                try:
                    if signal.getsignal(sig) == self._handle:
                        signal.signal(sig, prev if prev is not None
                                      else signal.SIG_DFL)
                except (ValueError, TypeError):
                    pass
            self._previous.clear()
            self._installed = False
