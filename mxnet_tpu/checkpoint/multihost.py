"""Multi-host checkpoint commit: per-host shards, one manifest.

A pod-scale kvstore='tpu' run has N processes with replicated
params/optimizer state but HOST-LOCAL error-feedback residuals and RNG
chains. Saving everything from rank 0 would both serialize the IO on
one host and silently drop every other host's residuals; saving
independently per host would leave N uncoordinated commit points.
The protocol here (Orbax/TensorStore shape, on the crash-safe
primitives of ``manifest.py``):

1. Every rank writes ITS OWN shard crash-safely (tmp+fsync+rename):
   ``<prefix>-<t>.shard<r>.params`` — its slice of the (replicated)
   param/aux keys, round-robin by sorted name so shard sizes balance;
   ``.shard<r>.states`` — the matching optimizer-state slice;
   ``.shard<r>.extra`` — its host-LOCAL extras (residuals, RNG) plus
   the replicated scheduler position; and a per-shard manifest
   ``.shard<r>.json`` recording sizes + CRC32s.
2. A barrier: nobody proceeds until every shard is durably in place.
   A host dying mid-write times the barrier out and NO manifest is
   ever published — the previous checkpoint stays the newest intact.
3. Rank 0 alone publishes the TOP manifest naming every shard file
   with its checksum — the single commit point. ``latest()`` therefore
   validates the FULL shard set: truncate or bit-flip any one host's
   shard and the whole tag is skipped in favor of the newest intact
   checkpoint.

Loading merges all shards (params/states are a disjoint partition);
each rank re-seeds its own residuals/RNG from its own shard. The
functions take explicit ``rank``/``world`` so a single process can
exercise the full protocol (tests), with the barrier injected only in
real multi-process worlds.
"""
from __future__ import annotations

import logging
import os
import pickle
import time
import zlib

import numpy as _np

from . import manifest as _mf
from .. import telemetry as _telemetry

__all__ = ["shard_names", "write_shard", "commit_sharded",
           "write_checkpoint_sharded", "load_sharded",
           "is_sharded_manifest"]

SHARD_BYTES = _telemetry.REGISTRY.counter(
    "checkpoint_shard_bytes",
    "bytes this process committed to its own checkpoint shards",
    unit="bytes")
SHARD_WRITES = _telemetry.REGISTRY.counter(
    "checkpoint_shard_writes",
    "checkpoint shards durably written by this process")
SHARD_BARRIER_MS = _telemetry.REGISTRY.histogram(
    "checkpoint_shard_barrier_ms",
    "wall time this process waited for the all-shards-durable barrier "
    "before the rank-0 manifest commit", unit="ms")


def shard_names(names, rank, world):
    """Deterministic round-robin partition of sorted ``names`` — every
    rank computes the same disjoint cover with balanced cardinality."""
    return sorted(names)[rank::world]


def _shard_manifest_path(prefix, tag, rank):
    return "%s-%s.shard%d.json" % (prefix, _mf.tag_str(tag), rank)


def is_sharded_manifest(man):
    return bool(man) and int(man.get("world", 1) or 1) > 1


def write_shard(state, prefix, tag, rank, world):
    """Write rank ``rank``'s shard of ``state`` crash-safely and publish
    its per-shard manifest. Returns the shard record. Pure-local: no
    barrier, no rank-0 privilege (except the shared symbol file, which
    only rank 0 writes)."""
    from ..ndarray import NDArray
    from ..serialization import save_ndarray_file
    t = _mf.tag_str(tag)
    files, tensors, total = {}, {}, 0

    if rank == 0 and state.get("symbol_json"):
        # same skip-if-unchanged treatment as the single-host writer
        sym_path = "%s-symbol.json" % prefix
        blob = state["symbol_json"].encode()
        try:
            with open(sym_path, "rb") as f:
                unchanged = f.read() == blob
        except OSError:
            unchanged = False
        if unchanged:
            nbytes, crc = len(blob), zlib.crc32(blob) & 0xFFFFFFFF
        else:
            nbytes, crc = _mf.atomic_write(sym_path, blob)
        files["symbol"] = {"file": os.path.basename(sym_path),
                           "bytes": nbytes, "crc32": crc}

    mine_args = shard_names(state["args"], rank, world)
    mine_auxs = shard_names(state["auxs"], rank, world)
    save_dict = {"arg:%s" % k: state["args"][k] for k in mine_args}
    save_dict.update({"aux:%s" % k: state["auxs"][k] for k in mine_auxs})
    for key, v in save_dict.items():
        raw = _np.ascontiguousarray(v)
        tensors[key] = {"crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                        "bytes": raw.nbytes, "shape": list(raw.shape),
                        "dtype": str(raw.dtype)}
    params_path = "%s-%s.shard%d.params" % (prefix, t, rank)
    nbytes, crc = _mf.atomic_write(
        params_path,
        writer=lambda tmp: save_ndarray_file(
            tmp, {k: NDArray(_np.ascontiguousarray(v))
                  for k, v in save_dict.items()}))
    files["params"] = {"file": os.path.basename(params_path),
                       "bytes": nbytes, "crc32": crc}
    total += nbytes

    if state.get("states") is not None:
        mine = shard_names(state["states"], rank, world)
        blob = pickle.dumps({k: state["states"][k] for k in mine})
        states_path = "%s-%s.shard%d.states" % (prefix, t, rank)
        nbytes, crc = _mf.atomic_write(states_path, blob)
        files["states"] = {"file": os.path.basename(states_path),
                           "bytes": nbytes, "crc32": crc}
        total += nbytes

    extra = state.get("extra") or {}
    if any(v is not None for v in extra.values()):
        blob = pickle.dumps(extra)
        extra_path = "%s-%s.shard%d.extra" % (prefix, t, rank)
        nbytes, crc = _mf.atomic_write(extra_path, blob)
        files["extra"] = {"file": os.path.basename(extra_path),
                          "bytes": nbytes, "crc32": crc}
        total += nbytes

    rec = {"rank": rank, "world": world, "files": files,
           "tensors": tensors, "total_bytes": total}
    _mf.atomic_write(_shard_manifest_path(prefix, tag, rank),
                     __import__("json").dumps(rec, sort_keys=True).encode())
    SHARD_BYTES.inc(total)
    SHARD_WRITES.inc()
    return rec


def commit_sharded(prefix, tag, world, meta=None):
    """Rank 0's commit: fold every per-shard manifest into ONE top
    manifest naming all shard files (the single commit point), then
    drop the per-shard manifests (they were only the handoff). Raises
    OSError when a shard manifest is missing/undecodable — the caller's
    barrier guarantees that never happens in a healthy job."""
    import json
    files, tensors, total = {}, {}, 0
    for r in range(world):
        path = _shard_manifest_path(prefix, tag, r)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            raise OSError("checkpoint commit: shard manifest %s is "
                          "missing or unreadable (%s)" % (path, e))
        for role, file_rec in rec["files"].items():
            name = role if role == "symbol" else "shard%d_%s" % (r, role)
            files[name] = file_rec
        tensors.update(rec.get("tensors", {}))
        total += int(rec.get("total_bytes", 0))
    base_meta = {"world": world, "total_bytes": total, "time": time.time(),
                 "library": "mxnet_tpu"}
    base_meta.update(meta or {})
    man = _mf.write_manifest(prefix, tag, files, tensors, base_meta)
    for r in range(world):
        try:
            os.unlink(_shard_manifest_path(prefix, tag, r))
        except OSError:
            pass
    return man


def write_checkpoint_sharded(state, prefix, tag):
    """The real multi-process commit (called from
    ``snapshot.write_checkpoint`` when the captured state spans a
    world): write my shard -> barrier -> rank 0 publishes -> barrier.
    Every rank returns the committed manifest."""
    from ..kvstore_tpu import dist
    rank = int(state.get("rank", 0) or 0)
    world = int(state.get("world", 1) or 1)
    write_shard(state, prefix, tag, rank, world)
    t0 = time.perf_counter()
    dist.barrier("ckpt-shards")
    SHARD_BARRIER_MS.observe((time.perf_counter() - t0) * 1e3)
    if rank == 0:
        meta = {"epoch": state.get("epoch"), "step": state.get("step"),
                "rng": state.get("rng")}
        commit_sharded(prefix, tag, world, meta)
        _telemetry.RECORDER.note("checkpoint_sharded_commit",
                                 tag=int(tag), world=world)
    dist.barrier("ckpt-commit")
    man = _mf.read_manifest(prefix, tag)
    if man is None:
        raise OSError("checkpoint %s tag %s: manifest did not appear "
                      "after the commit barrier" % (prefix, tag))
    return man


# ----------------------------------------------------------------------
# loading
# ----------------------------------------------------------------------
def _shard_roles(man, role):
    """[(rank, file_rec)] for one role, ascending rank."""
    out = []
    for name, rec in man.get("files", {}).items():
        if name.startswith("shard") and name.endswith("_" + role):
            out.append((int(name[len("shard"):-len("_" + role)]), rec))
    return sorted(out)


def load_sharded(prefix, man, rank=None, want_params=True):
    """Merge a sharded checkpoint: ``(arg_params, aux_params,
    states|None, extra)``. Params/states merge across ALL shards (a
    disjoint partition); ``extra`` (residuals, host RNG) comes from
    shard ``rank``'s file — host-local state belongs to the rank that
    wrote it. A ``rank`` beyond the saved world (resume with a
    different topology) degrades to shard 0's extras minus residuals,
    with a warning. ``want_params=False`` skips the param-shard reads
    (callers that already merged them via ``checkpoint.load``)."""
    from .. import ndarray as nd
    base_dir = os.path.dirname(prefix)
    arg_params, aux_params = {}, {}
    if want_params:
        for _r, rec in _shard_roles(man, "params"):
            for k, v in nd.load(os.path.join(base_dir,
                                             rec["file"])).items():
                tp, name = k.split(":", 1)
                if tp == "arg":
                    arg_params[name] = v
                elif tp == "aux":
                    aux_params[name] = v
    states = None
    state_shards = _shard_roles(man, "states")
    if state_shards:
        states = {}
        for _r, rec in state_shards:
            with open(os.path.join(base_dir, rec["file"]), "rb") as f:
                states.update(pickle.load(f))
    extra = {}
    extra_shards = dict(_shard_roles(man, "extra"))
    world = int(man.get("world", 1) or 1)
    if rank is None:
        rank = 0
    drop_residuals = False
    if rank >= world or rank not in extra_shards:
        if extra_shards:
            logging.warning(
                "checkpoint %s: restoring rank %d from a world-%d "
                "checkpoint — host-local residuals cannot be remapped "
                "and are dropped (replicated extras come from shard 0)",
                prefix, rank, world)
            rank = min(extra_shards)
            drop_residuals = True
    if rank in extra_shards:
        with open(os.path.join(base_dir, extra_shards[rank]["file"]),
                  "rb") as f:
            extra = pickle.load(f)
        if drop_residuals:
            extra.pop("residuals", None)
    return arg_params, aux_params, states, extra
