"""GSPMD sharded checkpoints: shard-local slices with ABSOLUTE bounds.

``checkpoint/multihost.py`` shards the training state by *rank* — each
host writes whatever tensors it owns whole, and reload assumes the same
world shape.  GSPMD-sharded tensors (mx.sharding) need the orthogonal
protocol: a parameter partitioned over an ``mp`` axis exists as N
device-local slices, and a checkpoint taken at dp=4 x mp=2 must restore
into dp=8 x mp=1, a single device, or any future mesh.

So every saved slice records its ABSOLUTE index bounds ``(lo, hi)`` per
dimension (the same trick embedding/checkpoint.py uses for row-sharded
tables).  Reload assembles the full logical tensor from whatever slices
exist — the saving mesh never constrains the loading mesh — and the
caller (or ``Executor._install_param_shardings`` at the next bind)
re-places it under the current mesh.  Files ride the PR 7 manifest
protocol (atomic publish, file+tensor CRC32s, newest-intact fallback).

Layout for tag T:
  ``<prefix>-<T>.sharded.npz``   — one npz of raw slices, keys ``s<i>``
  ``<prefix>-<T>.ckpt.json``     — manifest; each tensor record carries
                                   shape/dtype and its slice list
                                   ``[{"key", "lo", "hi"}, ...]``
"""
from __future__ import annotations

import os
import zlib

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from . import manifest as _mf

__all__ = ["save_sharded", "load_sharded", "latest_sharded"]

_DATA_SUFFIX = ".sharded.npz"


def _data_path(prefix, tag):
    return "%s-%s%s" % (prefix, _mf.tag_str(tag), _DATA_SUFFIX)


def _unique_slices(data):
    """[(bounds, numpy slice)] covering ``data`` exactly once: walk the
    addressable shards, normalize each shard.index to absolute (lo, hi)
    bounds, and drop replicas (same bounds on another device)."""
    shards = getattr(data, "addressable_shards", None)
    if not shards:
        arr = _np.asarray(data)
        return [(tuple((0, s) for s in arr.shape), arr)]
    out, seen = [], set()
    shape = tuple(data.shape)
    for sh in shards:
        idx = sh.index if isinstance(sh.index, tuple) else (sh.index,)
        bounds = []
        for dim, sl in enumerate(idx):
            lo = 0 if sl.start is None else int(sl.start)
            hi = shape[dim] if sl.stop is None else int(sl.stop)
            bounds.append((lo, hi))
        # rank-0 (scalar) shards have an empty index: one replica total
        bounds = tuple(bounds)
        if bounds in seen:
            continue
        seen.add(bounds)
        out.append((bounds, _np.asarray(sh.data)))
    return out


def save_sharded(prefix, tag, tensors, meta=None):
    """Checkpoint a {key: NDArray | jax.Array | numpy} dict, writing
    only the unique device-local slices of each tensor.  Returns the
    manifest.  ``tensors`` keys are free-form — the fused-fit
    convention is ``param:<name>``, ``state:<name>:<leaf>``,
    ``residual:<name>`` (docs/SHARDING.md)."""
    slices = {}          # npz key -> numpy slice
    index = {}           # tensor key -> manifest record
    n = 0
    for key in sorted(tensors):
        data = tensors[key]
        data = data._data if isinstance(data, NDArray) else data
        recs = []
        for bounds, arr in _unique_slices(data):
            skey = "s%d" % n
            n += 1
            slices[skey] = arr
            recs.append({"key": skey,
                         "lo": [int(b[0]) for b in bounds],
                         "hi": [int(b[1]) for b in bounds]})
        index[key] = {
            "shape": [int(s) for s in getattr(data, "shape", ())],
            "dtype": str(_np.dtype(getattr(data, "dtype", "float32"))),
            "slices": recs,
            "crc32": _tensor_crc(recs, slices),
        }
    path = _data_path(prefix, tag)

    def _writer(tmp):
        with open(tmp, "wb") as f:
            _np.savez(f, **slices)

    nbytes, crc = _mf.atomic_write(path, writer=_writer)
    files = {"sharded": {"file": os.path.basename(path),
                         "bytes": nbytes, "crc32": crc}}
    return _mf.write_manifest(prefix, tag, files, index,
                              meta=dict(meta or {}, kind="sharded"))


def _tensor_crc(recs, slices):
    crc = 0
    for r in recs:
        crc = zlib.crc32(_np.ascontiguousarray(slices[r["key"]]).tobytes(),
                         crc)
    return crc & 0xFFFFFFFF


def load_sharded(prefix, tag=None, manifest=None):
    """Assemble {key: numpy array} from a sharded checkpoint, whatever
    mesh (or no mesh) wrote it.  With ``tag=None`` resumes from the
    newest intact manifest.  Every tensor re-verifies its slice CRC."""
    if manifest is None:
        manifest = latest_sharded(prefix) if tag is None \
            else _mf.read_manifest(prefix, tag)
    if manifest is None:
        raise MXNetError("no sharded checkpoint found at prefix %r"
                         % (prefix,))
    path = _data_path(prefix, manifest["tag"])
    out = {}
    try:
        with _np.load(path) as npz:
            slices = {k: npz[k] for k in npz.files}
    except Exception as e:      # truncated/corrupt zip, missing file
        raise MXNetError("sharded checkpoint %s unreadable: %s"
                         % (path, e))
    for key, rec in manifest["tensors"].items():
        if _tensor_crc(rec["slices"], slices) != rec["crc32"]:
            raise MXNetError("sharded checkpoint %s: tensor %r failed "
                             "CRC validation" % (path, key))
        shape = tuple(rec["shape"])
        dst = _np.empty(shape, dtype=rec["dtype"])
        for s in rec["slices"]:
            window = tuple(slice(lo, hi)
                           for lo, hi in zip(s["lo"], s["hi"]))
            dst[window] = slices[s["key"]]
        out[key] = dst
    return out


def latest_sharded(prefix):
    """Newest intact manifest under ``prefix`` that is a sharded
    checkpoint (kind == 'sharded')."""
    for tag in reversed(_mf.list_tags(prefix)):
        man = _mf.read_manifest(prefix, tag)
        if man is None or man.get("kind") != "sharded":
            continue
        if _mf.validate(prefix, man):
            return man
    return None
