"""Checkpoint manifests: crash-safe file primitives + integrity index.

Every checkpoint commit is a JSON manifest (``<prefix>-<tag>.ckpt.json``)
naming the data files it covers with file-level AND per-tensor CRC32
checksums. The write protocol is the classic atomic-publish sequence —
data files first (tmp + fsync + rename), manifest rename LAST — so a
crash at any byte leaves either the previous checkpoint intact or a
garbage tmp file that validation never looks at. ``latest(prefix)``
walks tags newest-first, checksum-validates each candidate, and falls
back to the newest intact one: a truncated or bit-flipped newest
checkpoint can never abort a resume (Orbax/TensorStore shape, see
docs/CHECKPOINT.md).
"""
from __future__ import annotations

import json
import os
import re
import threading
import zlib

__all__ = ["MANIFEST_FORMAT", "manifest_path", "tag_str", "atomic_write",
           "crc32_file", "write_manifest", "read_manifest", "validate",
           "list_tags", "latest", "delete_checkpoint"]

MANIFEST_FORMAT = 1
_CHUNK = 1 << 20


def tag_str(tag):
    """Zero-padded tag, the ``%04d`` of the legacy ``%s-%04d.params``
    contract (tags past 9999 simply widen)."""
    return "%04d" % int(tag)


def manifest_path(prefix, tag):
    return "%s-%s.ckpt.json" % (prefix, tag_str(tag))


def _fsync_dir(path):
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path, data=None, writer=None):
    """Write ``data`` bytes (or stream through ``writer(tmp_path)``) to
    ``path`` crash-safely: tmp file in the same directory, fsync, atomic
    rename, directory fsync. Returns (bytes_written, crc32). The tmp
    name carries pid AND thread id: the async writer and an emergency
    save may target the same prefix from different threads."""
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), threading.get_ident())
    try:
        if writer is not None:
            writer(tmp)
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
            nbytes, crc = crc32_file(tmp)
        else:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            nbytes, crc = len(data), zlib.crc32(data) & 0xFFFFFFFF
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path)
    return nbytes, crc


def crc32_file(path):
    """(size, crc32) of a file, streamed."""
    crc, n = 0, 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return n, crc & 0xFFFFFFFF


def write_manifest(prefix, tag, files, tensors, meta=None):
    """Commit point: publish the manifest naming ``files``
    ({role: {"file", "bytes", "crc32"}}) and ``tensors``
    ({key: {"crc32", "bytes", "shape", "dtype"}}). Everything it names
    must already be durably in place."""
    doc = {"format": MANIFEST_FORMAT, "tag": int(tag),
           "files": files, "tensors": tensors}
    if meta:
        doc.update(meta)
    path = manifest_path(prefix, tag)
    atomic_write(path, json.dumps(doc, sort_keys=True).encode())
    return doc


def read_manifest(prefix, tag):
    """Parse one manifest; None when missing/undecodable (a torn
    manifest is just 'not a checkpoint', never an error)."""
    try:
        with open(manifest_path(prefix, tag)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "files" not in doc:
        return None
    return doc


def validate(prefix, manifest):
    """File-level integrity: every file the manifest names exists with
    the recorded size and CRC32. (Per-tensor checksums are re-verified
    at load time by ``snapshot.load``.)

    The shared ``-symbol.json`` is exempt: it is overwritten by every
    save, so a run that resumes with a changed graph under the same
    prefix would otherwise invalidate EVERY older manifest at once and
    collapse the newest-intact fallback chain."""
    if manifest is None:
        return False
    base_dir = os.path.dirname(prefix)
    for role, rec in manifest.get("files", {}).items():
        if role == "symbol":
            continue
        path = os.path.join(base_dir, rec["file"])
        try:
            nbytes, crc = crc32_file(path)
        except OSError:
            return False
        if nbytes != rec["bytes"] or crc != rec["crc32"]:
            return False
    return True


def list_tags(prefix):
    """All manifest tags for ``prefix``, ascending (no validation)."""
    base_dir = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    rx = re.compile(r"^%s-(\d{4,})\.ckpt\.json$" % re.escape(base))
    tags = []
    try:
        names = os.listdir(base_dir)
    except OSError:
        return []
    for name in names:
        m = rx.match(name)
        if m:
            tags.append(int(m.group(1)))
    return sorted(tags)


def latest(prefix, validate_files=True):
    """Newest INTACT manifest for ``prefix`` (checksum-validated), or
    None. Corrupt/truncated newer checkpoints are skipped with a
    warning — resume always falls back to the newest one that passes."""
    import logging
    for tag in reversed(list_tags(prefix)):
        man = read_manifest(prefix, tag)
        if man is None:
            logging.warning("checkpoint %s: unreadable manifest, skipping",
                            manifest_path(prefix, tag))
            continue
        if validate_files and not validate(prefix, man):
            logging.warning("checkpoint %s: checksum validation failed "
                            "(truncated or corrupt), falling back",
                            manifest_path(prefix, tag))
            continue
        return man
    return None


def delete_checkpoint(prefix, tag):
    """Remove one checkpoint: manifest first (so it stops being a
    candidate), then its data files. Shared files (``-symbol.json``)
    are never named in ``files`` with role ``symbol`` removed here."""
    man = read_manifest(prefix, tag)
    try:
        os.unlink(manifest_path(prefix, tag))
    except OSError:
        pass
    if man is None:
        return
    base_dir = os.path.dirname(prefix)
    for role, rec in man.get("files", {}).items():
        if role == "symbol":
            continue        # shared across tags
        try:
            os.unlink(os.path.join(base_dir, rec["file"]))
        except OSError:
            pass
