"""Full-training-state capture and restore (docs/CHECKPOINT.md).

``capture(module)`` reads every piece of live training state at a fit
step sync boundary — parameters, aux states, updater-keyed optimizer
state, the 2-bit error-feedback residuals (from whichever engine owns
them right now: the fused fit step's donated dict, the bucketed
kvstore's flat buffers, or the eager per-(key,dev) dict), the global
RNG chain, the lr-scheduler position and update counts, epoch/step —
and materializes it all as host numpy arrays. That device→host copy is
the ONLY part that blocks the training thread; serialization and IO
happen wherever the caller runs ``write_checkpoint`` (the async writer
thread, normally).

State keys are canonical **param names** regardless of which updater
key scheme (kvstore name keys / local interleaved int keys) the saving
module ran, so a checkpoint taken on one path resumes on the other —
``Module.save/load_optimizer_states`` applies the same translation.

File layout per checkpoint ``<prefix>``, tag ``<t>`` (``%04d``):

* ``<prefix>-symbol.json``   — shared; the legacy symbol file
* ``<prefix>-<t>.params``    — the LEGACY ``arg:``/``aux:`` params file
  (loadable by ``Module.load`` / ``model.load_checkpoint`` unchanged)
* ``<prefix>-<t>.states``    — legacy pickled optimizer-state dict,
  canonically name-keyed
* ``<prefix>-<t>.extra``     — pickle: residuals, host RNG state,
  lr-scheduler, per-key update counts
* ``<prefix>-<t>.ckpt.json`` — the manifest (commit point)
"""
from __future__ import annotations

import logging
import os
import pickle
import time
import zlib

import numpy as _np

from . import manifest as _mf
from .. import telemetry as _telemetry

__all__ = ["capture", "capture_params", "write_checkpoint", "load",
           "restore"]

RESTORE_MS = _telemetry.REGISTRY.histogram(
    "checkpoint_restore_ms",
    "wall time of a full training-state restore (read + verify + place)",
    unit="ms")


def _asnumpy(v):
    """Materialize one state value on the host (NDArray / jax / numpy /
    tuple-of-those / None)."""
    if v is None:
        return None
    if isinstance(v, tuple):
        return tuple(_asnumpy(x) for x in v)
    if hasattr(v, "asnumpy"):
        return _np.asarray(v.asnumpy())
    return _np.asarray(v)


def _as_ndarray(v):
    from ..ndarray import NDArray
    if v is None:
        return None
    if isinstance(v, tuple):
        return tuple(_as_ndarray(x) for x in v)
    if isinstance(v, NDArray):
        return v
    return NDArray(_np.ascontiguousarray(v))


def _plain_kvstore(module):
    """The module's KVStore when its weights/residuals are process-
    local (or replicated-deterministic) state this checkpointer may
    capture — the plain local stores AND kvstore='tpu'. Legacy dist
    stores keep server-side persistence and return None."""
    kv = getattr(module, "_kvstore", None)
    return kv if getattr(kv, "_captures_local_state", False) else None


def _capture_residuals(module):
    """Error-feedback residuals as {(key, dev): numpy}, read from
    whichever engine currently owns them (fused step > bucketed flat
    buffers > eager per-(key,dev) dict) WITHOUT disturbing ownership —
    a checkpoint must not change what the next step computes."""
    out = {}
    ff = getattr(module, "_fused_fit", None)
    if ff is not None and getattr(ff, "_residuals", None):
        for name, r in ff._residuals.items():
            # MUST copy: np.asarray of a CPU jax array is a zero-copy
            # view, and this buffer is DONATED to the next fused step —
            # an aliasing view would let the writer serialize
            # reused-buffer garbage
            out[(name, 0)] = _np.array(r, copy=True)
    kv = _plain_kvstore(module)
    if kv is not None:
        eng = kv._engine
        if eng is not None:
            for keys_tuple, rec in eng._flat_res.items():
                for d, flat in enumerate(rec["res"]):
                    flat = _np.asarray(flat)
                    for key, (off, size, shape) in zip(keys_tuple,
                                                       rec["layout"]):
                        out.setdefault(
                            (key, d),
                            flat[off:off + size].reshape(shape).copy())
        for (key, d), arr in kv._compression_residuals.items():
            out.setdefault((key, d), arr.asnumpy())
    return out


def _capture_optimizer(module):
    """(name-keyed states, extra-dict pieces) from the live updater, or
    (None, {}) when the module has no picklable optimizer state."""
    from ..optimizer import Updater
    updater = None
    if getattr(module, "optimizer_initialized", False):
        try:
            updater = module._live_updater()
        except AttributeError:
            updater = getattr(module, "_updater", None)
    if not isinstance(updater, Updater):
        return None, {}
    try:
        name_to_live, _ = module._opt_state_key_maps()
    except AttributeError:
        name_to_live = {k: k for k in updater.states}
    live_to_name = {lk: n for n, lk in name_to_live.items()}
    states = {live_to_name.get(k, k): _asnumpy(v)
              for k, v in updater.states.items()}
    optimizer = updater.optimizer
    counts = {live_to_name.get(k, k): int(v)
              for k, v in optimizer._index_update_count.items()}
    extra = {"index_update_count": counts,
             "num_update": int(optimizer.num_update),
             "lr_scheduler": optimizer.lr_scheduler}
    return states, extra


def _capture_world(kv):
    """(world, rank) for the multi-host sharded commit — engaged only
    when the module trains over a multi-process kvstore='tpu' (other
    multi-process configs, e.g. async PS workers, keep per-process
    full checkpoints under their own prefixes)."""
    from ..kvstore_tpu import KVStoreTPU
    if isinstance(kv, KVStoreTPU) and kv.num_workers > 1:
        return kv.num_workers, kv.rank
    return 1, 0


def capture(module, epoch=None, step=None, include_optimizer=True):
    """Snapshot the complete training state of ``module`` as host
    arrays. Runs on the training thread; blocks only for the
    device→host copies (no IO, no serialization, no compiled-program
    dispatch — the zero-retrace witnesses stay flat)."""
    from .. import random as _random
    kv = _plain_kvstore(module)
    if kv is not None:
        # flush pending async buckets so states/weights are post-step
        kv._flush_pending()
    arg_params, aux_params = module.get_params()
    world, rank = _capture_world(kv)
    state = {
        "symbol_json": (module.symbol.tojson()
                        if getattr(module, "symbol", None) is not None
                        else None),
        "args": {k: _np.asarray(v.asnumpy())
                 for k, v in (arg_params or {}).items()},
        "auxs": {k: _np.asarray(v.asnumpy())
                 for k, v in (aux_params or {}).items()},
        "epoch": epoch, "step": step,
        "rng": _rng_manifest_state(_random),
        "world": world, "rank": rank,
    }
    extra = {"host_rng": _rng_host_state(_random)}
    if include_optimizer:
        states, opt_extra = _capture_optimizer(module)
        state["states"] = states
        extra.update(opt_extra)
    else:
        state["states"] = None
    residuals = _capture_residuals(module)
    if residuals:
        extra["residuals"] = residuals
    scaler = getattr(module, "_loss_scaler", None)
    if scaler is not None:
        # loss-scaler triple is training state: resuming a bf16 run at
        # init scale would re-run the warmup backoffs (capture is a
        # sync boundary, so state_dict's publish() readback is free)
        extra["loss_scaler"] = scaler.state_dict()
    fused = getattr(module, "_fused_fit", None)
    if fused is not None:
        # capture is a sync boundary: publish the in-launch numerics
        # sentinels so the checkpoint tick doubles as a sentinel read
        fused.publish_sentinels()
    state["extra"] = extra
    return state


def capture_params(arg_params, aux_params=None, symbol=None, epoch=None,
                   step=None):
    """A params-only snapshot from raw dicts (the ``do_checkpoint``
    epoch-callback form — no module required)."""
    return {
        "symbol_json": symbol.tojson() if symbol is not None else None,
        "args": {k: _asnumpy(v) for k, v in (arg_params or {}).items()},
        "auxs": {k: _asnumpy(v) for k, v in (aux_params or {}).items()},
        "states": None, "extra": {}, "epoch": epoch, "step": step,
        "rng": None,
    }


def _rng_manifest_state(random_mod):
    st = random_mod.get_state()
    return {"seed": st["seed"], "key": st["key"]}


def _rng_host_state(random_mod):
    return random_mod.get_state()["host"]


# ----------------------------------------------------------------------
# serialization + crash-safe write (runs on the writer thread)
# ----------------------------------------------------------------------
def write_checkpoint(state, prefix, tag):
    """Serialize ``state`` and publish checkpoint ``tag`` atomically.
    Returns the committed manifest. Total bytes written are in
    ``manifest["total_bytes"]``. A state captured over a multi-process
    kvstore='tpu' world commits through the sharded multi-host protocol
    instead (one shard per host, rank-0 manifest — multihost.py)."""
    if int(state.get("world", 1) or 1) > 1:
        from . import multihost as _mh
        return _mh.write_checkpoint_sharded(state, prefix, tag)
    from ..ndarray import NDArray
    from ..serialization import save_ndarray_file
    base_dir = os.path.dirname(prefix)
    files, tensors, total = {}, {}, 0

    if state.get("symbol_json"):
        sym_path = "%s-symbol.json" % prefix
        blob = state["symbol_json"].encode()
        try:                    # shared file: skip the rewrite when
            with open(sym_path, "rb") as f:      # content is unchanged
                unchanged = f.read() == blob
        except OSError:
            unchanged = False
        if unchanged:
            nbytes = len(blob)
            crc = zlib.crc32(blob) & 0xFFFFFFFF
        else:
            nbytes, crc = _mf.atomic_write(sym_path, blob)
        files["symbol"] = {"file": os.path.relpath(sym_path, base_dir or "."),
                           "bytes": nbytes, "crc32": crc}

    save_dict = {"arg:%s" % k: v for k, v in state["args"].items()}
    save_dict.update({"aux:%s" % k: v for k, v in state["auxs"].items()})
    for key, v in save_dict.items():
        raw = _np.ascontiguousarray(v)
        # crc32 over the buffer protocol — no tobytes() copy of the
        # whole model per save
        tensors[key] = {"crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                        "bytes": raw.nbytes, "shape": list(raw.shape),
                        "dtype": str(raw.dtype)}
    params_path = "%s-%s.params" % (prefix, _mf.tag_str(tag))
    nbytes, crc = _mf.atomic_write(
        params_path,
        writer=lambda tmp: save_ndarray_file(
            tmp, {k: NDArray(_np.ascontiguousarray(v))
                  for k, v in save_dict.items()}))
    files["params"] = {"file": os.path.basename(params_path),
                       "bytes": nbytes, "crc32": crc}
    total += nbytes

    if state.get("states") is not None:
        blob = pickle.dumps({k: _as_ndarray(v)
                             for k, v in state["states"].items()})
        states_path = "%s-%s.states" % (prefix, _mf.tag_str(tag))
        nbytes, crc = _mf.atomic_write(states_path, blob)
        files["states"] = {"file": os.path.basename(states_path),
                           "bytes": nbytes, "crc32": crc}
        total += nbytes

    extra = state.get("extra") or {}
    if any(v is not None for v in extra.values()):
        blob = pickle.dumps(extra)
        extra_path = "%s-%s.extra" % (prefix, _mf.tag_str(tag))
        nbytes, crc = _mf.atomic_write(extra_path, blob)
        files["extra"] = {"file": os.path.basename(extra_path),
                          "bytes": nbytes, "crc32": crc}
        total += nbytes

    meta = {"epoch": state.get("epoch"), "step": state.get("step"),
            "rng": state.get("rng"), "time": time.time(),
            "total_bytes": total, "library": "mxnet_tpu"}
    return _mf.write_manifest(prefix, tag, files, tensors, meta)


# ----------------------------------------------------------------------
# load / restore
# ----------------------------------------------------------------------
def _resolve(prefix, tag):
    if tag is None:
        man = _mf.latest(prefix)
        if man is None:
            raise IOError("no intact checkpoint found for prefix %r"
                          % prefix)
        return man
    man = _mf.read_manifest(prefix, tag)
    if man is None or not _mf.validate(prefix, man):
        raise IOError("checkpoint %s is missing or corrupt"
                      % _mf.manifest_path(prefix, tag))
    return man


def _verify_tensors(manifest, arg_params, aux_params, prefix):
    for kind, params in (("arg", arg_params), ("aux", aux_params)):
        for name, v in params.items():
            rec = manifest.get("tensors", {}).get("%s:%s" % (kind, name))
            if rec is None:
                continue
            raw = _np.ascontiguousarray(v.asnumpy())
            if (zlib.crc32(raw) & 0xFFFFFFFF) != rec["crc32"]:
                raise IOError(
                    "checkpoint %s: tensor %s:%s fails its manifest "
                    "checksum" % (prefix, kind, name))


def load(prefix, tag=None, verify=True):
    """Load checkpoint content: ``(symbol|None, arg_params, aux_params,
    manifest)``. ``tag=None`` resolves via :func:`manifest.latest`
    (checksum-validated newest-intact fallback); per-tensor checksums
    re-verify after parse unless ``verify=False``."""
    from .. import model as _model
    man = _resolve(prefix, tag)
    from . import multihost as _mh
    if _mh.is_sharded_manifest(man):
        arg_params, aux_params, _states, _extra = _mh.load_sharded(
            prefix, man)
    else:
        arg_params, aux_params = _model.load_params(prefix, man["tag"])
    if verify:
        _verify_tensors(man, arg_params, aux_params, prefix)
    symbol = None
    if "symbol" in man.get("files", {}):
        from .. import symbol as _sym
        try:
            symbol = _sym.load("%s-symbol.json" % prefix)
        except Exception:
            symbol = None
    return symbol, arg_params, aux_params, man


def _load_extra(prefix, man):
    rec = man.get("files", {}).get("extra")
    if rec is None:
        return {}
    path = os.path.join(os.path.dirname(prefix), rec["file"])
    with open(path, "rb") as f:
        return pickle.load(f)


def _restore_residuals(module, residuals):
    """Seed restored error-feedback residuals so EITHER path picks them
    up: the kvstore per-(key,dev) dict is the shared reseed surface; a
    live fused step drops its stale donated dict and reseeds from the
    kvstore on its next launch."""
    import jax.numpy as jnp
    kv = _plain_kvstore(module)
    ff = getattr(module, "_fused_fit", None)
    if kv is not None:
        from ..ndarray import NDArray
        kv._sync_engine()          # flush + clear engine flat ownership
        for (key, dev), arr in residuals.items():
            kv._compression_residuals[(key, dev)] = NDArray(
                jnp.asarray(arr))
        if ff is not None:
            # discard, do NOT spill: the restored values must win
            ff._residuals = None
    elif ff is not None and getattr(ff, "_threshold", None) is not None:
        ff._residuals = {key: jnp.asarray(arr)
                         for (key, dev), arr in residuals.items()
                         if dev == 0}
    else:
        # e.g. resuming a compressed checkpoint on an uncompressed
        # config: nothing will consume error feedback here — say so
        # rather than dropping it silently
        logging.warning(
            "checkpoint.restore: checkpoint carries %d error-feedback "
            "residuals but this module has no compression engine to "
            "seed them into", len(residuals))


def restore(module, prefix, tag=None, load_optimizer=True, verify=True,
            logger=None):
    """Restore the complete training state of ``module`` from the
    newest intact checkpoint (or ``tag``). Returns the manifest (epoch/
    step under ``manifest["epoch"]``/``["step"]``).

    The module should be bound with its optimizer initialized for a
    full restore; a bare module gets params plus a deferred
    ``_preload_opt_states`` (the ``Module.load`` mechanism) and the
    optimizer-position extras are skipped with a warning."""
    log = logger or logging
    t0 = time.perf_counter()
    _, arg_params, aux_params, man = load(prefix, tag, verify=verify)
    tag = man["tag"]

    if getattr(module, "binded", False):
        module.set_params(arg_params, aux_params, allow_missing=False,
                          force_init=True, allow_extra=True)
        kv = _plain_kvstore(module)
        if kv is not None and getattr(module, "_update_on_kvstore", False):
            # the kvstore's own weight store is what eager pulls (and
            # fused rebinds) read — refresh it or the next update would
            # clobber the restored params with pre-restore weights
            for name, v in arg_params.items():
                if name in kv._store:
                    kv._store[name] = v.copy()
    else:
        module._arg_params = arg_params
        module._aux_params = aux_params
        module.params_initialized = True

    from . import multihost as _mh
    if _mh.is_sharded_manifest(man):
        import jax
        rank = jax.process_index()
        # params already merged by load() above — read only the
        # states/extra shards here
        _a, _b, merged_states, extra = _mh.load_sharded(
            prefix, man, rank=rank, want_params=False)
        states_path = None
        if load_optimizer and merged_states is not None:
            # Module.load_optimizer_states consumes a FILE in the
            # legacy pickle format — publish the merged partition
            # crash-safely next to the shards (also serves the
            # deferred _preload_opt_states path on a bare module).
            # Rank-unique name: every rank of a shared-FS world
            # restores concurrently, and atomic_write's tmp names are
            # only pid/thread-unique WITHIN a host
            states_path = "%s-%s.states.merged.r%d" % (
                prefix, _mf.tag_str(tag), rank)
            _mf.atomic_write(states_path, pickle.dumps(
                {k: _as_ndarray(v) for k, v in merged_states.items()}))
    else:
        states_rec = man.get("files", {}).get("states")
        states_path = (os.path.join(os.path.dirname(prefix),
                                    states_rec["file"])
                       if states_rec else None)
        extra = _load_extra(prefix, man)

    if load_optimizer and states_path is not None:
        if getattr(module, "optimizer_initialized", False):
            module.load_optimizer_states(states_path)
            if _mh.is_sharded_manifest(man):
                # the merged-states file was only the handoff into
                # load_optimizer_states — it is named in no manifest,
                # so rotation would never collect it (the deferred
                # _preload_opt_states branch below must keep it until
                # init_optimizer consumes it)
                try:
                    os.unlink(states_path)
                except OSError:
                    pass
            optimizer = getattr(module, "_optimizer", None)
            if optimizer is not None:
                counts = extra.get("index_update_count") or {}
                try:
                    name_to_live, _ = module._opt_state_key_maps()
                except AttributeError:
                    name_to_live = {}
                for name, n in counts.items():
                    optimizer._index_update_count[
                        name_to_live.get(name, name)] = int(n)
                optimizer.num_update = max(
                    optimizer.num_update,
                    int(extra.get("num_update", 0) or 0))
                sched = extra.get("lr_scheduler")
                if sched is not None:
                    optimizer.lr_scheduler = sched
        else:
            module._preload_opt_states = states_path
            log.warning("checkpoint.restore: optimizer not initialized; "
                        "states will preload at init_optimizer, but the "
                        "lr-scheduler position/update counts are only "
                        "restored on an initialized module")

    residuals = extra.get("residuals")
    if residuals:
        _restore_residuals(module, residuals)

    scaler_state = extra.get("loss_scaler")
    if scaler_state:
        from ..fused_update import DynamicLossScaler
        scaler = getattr(module, "_loss_scaler", None)
        if scaler is not None:
            scaler.load_state_dict(scaler_state)
        else:
            # fused fit not built yet: park the restored scaler on the
            # module; FusedFitStep.build picks it up before from_config
            module._loss_scaler = DynamicLossScaler.from_state(scaler_state)

    rng = man.get("rng")
    if rng is not None:
        from .. import random as _random
        _random.set_state({"seed": rng.get("seed", 0),
                           "key": rng.get("key"),
                           "host": extra.get("host_rng")})

    RESTORE_MS.observe((time.perf_counter() - t0) * 1e3)
    _telemetry.RECORDER.note("checkpoint_restore", tag=tag)
    return man
