"""mx.checkpoint — fault-tolerant async checkpointing (docs/CHECKPOINT.md).

The reference MXNet's ``save_checkpoint``/``do_checkpoint`` is a
blocking, non-atomic, params-only path: optimizer state, the 2-bit
error-feedback residuals, RNG and lr-scheduler position are all lost
on restart, silently biasing compressed training after resume. This
subsystem rebuilds it Orbax-style for a preemptible TPU fleet:

* **Full state** — params, aux states, updater-keyed optimizer state,
  error-feedback residuals (fused or eager owner), RNG chain,
  lr-scheduler position, epoch/step (``snapshot.capture``).
* **Async** — the training thread blocks only for the device→host
  snapshot (``checkpoint_block_ms``); serialization + IO run on a
  background writer (``writer.AsyncCheckpointWriter``).
* **Crash-safe** — tmp + fsync + atomic rename per file, a JSON
  manifest with per-tensor checksums as the commit point, keep-N
  rotation, retry-with-backoff on transient IO errors
  (``manifest.py``); :func:`latest` checksum-validates and falls back
  to the newest intact checkpoint.
* **Preemption** — a SIGTERM handler triggers an emergency synchronous
  save and graceful drain at the next step boundary
  (``preemption.PreemptionHandler``; wired by ``Module.fit``).
* **Legacy-compatible** — the ``<prefix>-%04d.params`` /
  ``-symbol.json`` / ``.states`` files are the reference layout:
  ``Module.load`` and ``model.load_checkpoint`` read them unchanged.

Quick use::

    mod.fit(data, num_epoch=10, checkpoint_every=500,
            checkpoint_prefix="ckpt/run7")          # async, in the loop

    mgr = checkpoint.CheckpointManager("ckpt/run7", module=mod)
    mgr.save(epoch=3, step=1500)                    # explicit async save
    mgr.drain()

    man = checkpoint.latest("ckpt/run7")            # newest INTACT
    checkpoint.restore(mod2, "ckpt/run7")           # full-state resume
"""
from __future__ import annotations

import logging
import os
import time

from . import manifest
from . import snapshot
from . import multihost
from . import sharded
from . import writer as writer_mod
from . import preemption
from .manifest import latest
from .snapshot import capture, capture_params, load, restore, \
    write_checkpoint
from .writer import AsyncCheckpointWriter, write_with_retry
from .preemption import PreemptionHandler
from .sharded import save_sharded, load_sharded, latest_sharded

__all__ = ["CheckpointManager", "AsyncCheckpointWriter",
           "PreemptionHandler", "latest", "load", "resolve_params",
           "restore", "save",
           "capture", "capture_params", "manifest", "snapshot",
           "multihost", "preemption", "sharded",
           "save_sharded", "load_sharded", "latest_sharded"]


def resolve_params(prefix, tag=None, epoch=None, what="reload"):
    """Resolve a checkpoint reference to ``(arg_params, aux_params,
    version)``: ``epoch`` loads the legacy ``prefix-%04d.params`` file
    directly, otherwise ``tag`` (or the newest checksum-intact
    checkpoint) resolves through the manifest.  IO/corruption failures
    raise ``MXNetError`` prefixed with ``what`` — the one resolution
    path shared by the serving and decode hot-reload surfaces."""
    from ..base import MXNetError
    if epoch is not None:
        from .. import model as _model
        try:
            arg_params, aux_params = _model.load_params(prefix, epoch)
        except OSError as e:
            raise MXNetError("%s: %s" % (what, e)) from e
        return arg_params, aux_params, int(epoch)
    try:
        _sym, arg_params, aux_params, man = load(prefix, tag)
    except OSError as e:
        raise MXNetError("%s: %s" % (what, e)) from e
    return arg_params, aux_params, int(man["tag"])


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def save(prefix, tag, arg_params, aux_params=None, symbol=None,
         epoch=None, step=None, keep=0, retries=3, backoff=0.05):
    """Synchronous params checkpoint from raw dicts: legacy
    ``<prefix>-%04d.params`` (+ ``-symbol.json``) plus the manifest.
    Returns the manifest."""
    state = capture_params(arg_params, aux_params, symbol=symbol,
                           epoch=epoch, step=step)
    return write_with_retry(state, prefix, tag, retries=retries,
                            backoff=backoff, keep=keep)


class CheckpointManager:
    """Drives checkpointing for one training run.

    Parameters
    ----------
    prefix : checkpoint path prefix (``dir/name``); files follow the
        legacy ``%s-%04d.*`` contract.
    module : the Module whose state is captured (optional for
        restore-only use).
    every : steps between automatic saves for :meth:`tick` (0 = only
        explicit :meth:`save` calls).
    keep : keep-N rotation (default env ``MXNET_CHECKPOINT_KEEP`` or 3;
        0 keeps everything).
    async_write : serialize+write on the background writer (default);
        False makes every save synchronous.
    save_optimizer : include updater-keyed optimizer state + extras.
    install_preemption : install the SIGTERM emergency-save handler
        (default env ``MXNET_CHECKPOINT_PREEMPT`` != 0).
    retries / backoff : transient-IO retry policy per write.
    """

    def __init__(self, prefix, module=None, every=0, keep=None,
                 async_write=True, save_optimizer=True,
                 install_preemption=None, retries=3, backoff=0.05,
                 logger=None):
        d = os.path.dirname(prefix)
        if d and not os.path.isdir(d):
            os.makedirs(d, exist_ok=True)
        self.prefix = prefix
        self.every = int(every or 0)
        self.keep = _env_int("MXNET_CHECKPOINT_KEEP", 3) \
            if keep is None else int(keep)
        self.logger = logger or logging
        self._module = module
        self._async = bool(async_write)
        self._save_optimizer = bool(save_optimizer)
        self._retries = retries
        self._backoff = backoff
        self._writer = AsyncCheckpointWriter(retries=retries,
                                             backoff=backoff,
                                             logger=self.logger)
        # continue the tag sequence past any existing checkpoints: a
        # resumed run must produce tags ABOVE the restore point, or
        # latest() would keep resolving to the pre-preemption state and
        # rotation would eat the resumed progress
        self._steps = max(manifest.list_tags(prefix), default=0)
        self._closed = False
        if install_preemption is None:
            install_preemption = os.environ.get(
                "MXNET_CHECKPOINT_PREEMPT", "1") != "0"
        self._preempt = PreemptionHandler(logger=self.logger).install() \
            if install_preemption else None

    # -- state ----------------------------------------------------------
    @property
    def preempted(self):
        return self._preempt is not None and self._preempt.triggered

    @property
    def preemption(self):
        return self._preempt

    # -- saving ---------------------------------------------------------
    def save(self, epoch=None, step=None, tag=None, block=False):
        """Snapshot the module now (blocking only for the device→host
        copy) and commit: on the writer thread normally, inline when
        ``block`` or the manager is synchronous. Returns the manifest
        for inline commits, None for queued ones."""
        if self._module is None:
            raise ValueError("CheckpointManager needs a module to save")
        t0 = time.perf_counter()
        state = capture(self._module, epoch=epoch, step=step,
                        include_optimizer=self._save_optimizer)
        if tag is None:
            tag = step
        if tag is None:
            self._steps += 1       # explicit save: advance, never
            tag = self._steps      # overwrite the newest tag
        try:
            self._steps = max(self._steps, int(tag))
        except (TypeError, ValueError):
            pass
        if block or not self._async:
            # inline commits are priced by checkpoint_save_ms only —
            # checkpoint_block_ms stays the async path's snapshot+enqueue
            # cost so its p50 vs fit_step_ms comparison keeps meaning
            return write_with_retry(state, self.prefix, tag,
                                    retries=self._retries,
                                    backoff=self._backoff,
                                    logger=self.logger, keep=self.keep)
        self._writer.submit(state, self.prefix, tag, keep=self.keep)
        writer_mod.BLOCK_MS.observe((time.perf_counter() - t0) * 1e3)
        return None

    def emergency_save(self, epoch=None, step=None):
        """Preemption path: drain queued async writes FIRST (two
        threads must never write the same prefix concurrently), then
        one synchronous full save of the freshest state."""
        self.logger.warning(
            "checkpoint: emergency save to %s (step %s)", self.prefix,
            step if step is not None else self._steps)
        self._writer.drain()
        man = self.save(epoch=epoch, step=step, block=True)
        import mxnet_tpu.telemetry as _telemetry
        _telemetry.RECORDER.note("checkpoint_emergency",
                                 tag=int(man["tag"]))
        return man

    def tick(self, epoch=None):
        """Per-step hook for the fit loop. Counts steps, saves every
        ``every``-th one, and on a pending preemption performs the
        emergency save + drain. Returns True when the loop should stop
        (preempted)."""
        self._steps += 1
        if self.preempted:
            self.emergency_save(epoch=epoch, step=self._steps)
            return True
        if self.every and self._steps % self.every == 0:
            self.save(epoch=epoch, step=self._steps)
        return False

    # -- reading --------------------------------------------------------
    def latest(self):
        return latest(self.prefix)

    def restore(self, module=None, tag=None):
        return restore(module if module is not None else self._module,
                       self.prefix, tag=tag, logger=self.logger)

    # -- lifecycle ------------------------------------------------------
    def drain(self, timeout=None):
        return self._writer.drain(timeout)

    def close(self, timeout=None):
        """Drain pending writes, stop the writer, restore signal
        handlers. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._writer.close(timeout)
        if self._preempt is not None:
            self._preempt.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
