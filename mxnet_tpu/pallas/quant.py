"""Fused 2-bit quantize kernel for the kvstore bucket path
(docs/KERNELS.md — the ISSUE 13 stretch kernel).

``kvstore_fused.two_bit_quantize`` is the error-feedback quantizer
shared by the bucketed kvstore step and the fused fit step:
``acc = residual + grad; q = select(acc, ±t, 0); new_res = acc - q``.
The XLA path emits that as a fusable elementwise chain; this kernel
computes both outputs in ONE pass over VMEM tiles — ``acc`` is never
materialized and each element is read once and written twice, the
minimum possible traffic for the op pair.  Dispatch rides
``MXNET_Q2BIT_IMPL`` through the same ``choose_impl`` contract as the
attention kernels; off-TPU the wrapper runs ``interpret=True``
(parity vs the XLA sequence is bit-exact — same select constants,
same subtract — pinned in tests/test_pallas.py).
"""
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:               # pragma: no cover — the pinned
    pl = pltpu = None           # toolchain always ships pallas

from .attention import _count_launch, _interpret_default

# one (rows, 128) f32 tile per grid step — 8 sublanes x 128 lanes is
# the native f32 VMEM tile; 64 rows keeps the working set tiny while
# amortizing the grid-step overhead
_TILE_ROWS = 64
_LANES = 128


def _two_bit_quantize_kernel(thr_ref, res_ref, grad_ref, q_ref,
                             out_res_ref):
    t = thr_ref[0]
    acc = res_ref[...] + grad_ref[...]
    q = jnp.where(acc > t, t,
                  jnp.where(acc < -t, -t, jnp.zeros_like(acc)))
    q_ref[...] = q
    out_res_ref[...] = acc - q


def two_bit_quantize_fused(residual, grad, threshold, *, interpret=None):
    """Error-feedback 2-bit quantize, one fused pass: returns
    ``(q, new_residual)`` with the exact op sequence (and therefore
    bit pattern) of ``kvstore_fused.two_bit_quantize``.  Accepts any
    shape; internally flattens and pads to (rows, 128) f32 tiles."""
    shape, dtype = grad.shape, grad.dtype
    n = 1
    for s in shape:
        n *= int(s)
    cols = _LANES
    rows = -(-n // cols)                    # ceil
    rows_pad = -(-rows // _TILE_ROWS) * _TILE_ROWS
    pad = rows_pad * cols - n

    def tile(a):
        flat = a.reshape(-1).astype(dtype)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), dtype)])
        return flat.reshape(rows_pad, cols)

    thr = jnp.asarray(threshold, dtype).reshape(1)
    _count_launch("two_bit_quantize_fused")
    grid = (rows_pad // _TILE_ROWS,)
    spec = pl.BlockSpec((_TILE_ROWS, cols), lambda i, t: (i, 0))
    fn = pl.pallas_call(
        _two_bit_quantize_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid,
            in_specs=[spec, spec], out_specs=[spec, spec]),
        out_shape=[jax.ShapeDtypeStruct((rows_pad, cols), dtype)] * 2,
        interpret=_interpret_default(interpret),
    )
    q, new_res = fn(thr, tile(residual), tile(grad))
    return (q.reshape(-1)[:n].reshape(shape),
            new_res.reshape(-1)[:n].reshape(shape))
