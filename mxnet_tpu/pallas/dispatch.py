"""Kernel-impl selection — one ``auto|<kernel>|xla`` contract.

``MXNET_ATTN_IMPL`` (flash), ``MXNET_PAGED_ATTN_IMPL`` (paged
decode/prefill) and ``MXNET_Q2BIT_IMPL`` (kvstore 2-bit quantize) all
route through :func:`choose_impl`, so the three knobs cannot drift:

* ``auto`` (default) — the kernel when the backend/geometry supports
  it profitably, the XLA reference path otherwise (the fallback bumps
  ``pallas_fallbacks{reason}``);
* ``xla`` — force the reference path (A/B runs);
* ``<kernel>`` — require the kernel; raise instead of silently
  measuring the wrong path when it cannot run.  The paged/quant
  kernels are *forceable anywhere* because ``interpret=True`` executes
  them on any backend — that is the tier-1/CI testing convention
  (docs/KERNELS.md).

The decisions here run at TRACE time (inside the enclosing jitted
program's Python), so they are per-program-construction, not
per-launch — same contract as ``_use_flash_attention`` always had.
"""
import os

from .. import telemetry as _telemetry
from ..telemetry.registry import RETRACE_SUPPRESS

# trace-time witnesses (docs/OBSERVABILITY.md glossary).  "Launches"
# counts kernel instantiations built into traced programs: steady-state
# dispatches ride the enclosing compiled program (decode_dispatches /
# dispatches_per_step witness those), so a warm serving loop adds zero.
PALLAS_LAUNCHES = _telemetry.REGISTRY.counter(
    "pallas_kernel_launches",
    "pallas kernel instantiations built into traced programs, "
    "labeled by `kernel`", vital=True)
PALLAS_FALLBACKS = _telemetry.REGISTRY.counter(
    "pallas_fallbacks",
    "auto-mode kernel selections that fell back to the XLA reference "
    "path, labeled by `reason`")
PALLAS_RETRACES = _telemetry.REGISTRY.counter(
    "pallas_kernel_retraces",
    "pallas kernel (re)builds — nonzero growth after warmup means a "
    "kernel is being reconstructed per call", vital=True)


def choose_impl(env_var, impl, kernel, supported, why, *,
                force_supported=None, fallback_reason="unsupported",
                count=True):
    """Shared ``auto|<kernel>|xla`` selection for a kernel knob.

    ``impl`` is the knob's raw value — the CALLER reads it with a
    literal env-var name (``os.environ.get("MXNET_X_IMPL", "auto")``)
    so the envknobs analyze pass can see the read site; ``env_var`` is
    only for error messages.  Returns True when the custom kernel
    should be used.  Raises ``ValueError`` for an unknown value, and
    when the kernel is forced (``<env_var>=<kernel>``) but cannot run —
    never silently measure the wrong path.  ``supported`` gates the
    ``auto`` choice; ``force_supported`` (default: same as
    ``supported``) gates the forced one — interpret-mode kernels pass
    ``force_supported=True`` since they run on any backend when
    explicitly requested.  ``count=False`` suppresses the fallback
    counter for observer-only calls (stats/bench polling must not
    inflate the per-trace witness).
    """
    if impl == "xla":
        return False
    if impl not in ("auto", kernel):
        raise ValueError("%s=%s; use auto|%s|xla" % (env_var, impl, kernel))
    if impl == kernel:
        ok = supported if force_supported is None else force_supported
        if not ok:
            raise ValueError("%s=%s but the kernel cannot run here (%s)"
                             % (env_var, impl, why))
        return True
    if not supported:
        if count and not RETRACE_SUPPRESS.on:   # not a registry re-lower
            PALLAS_FALLBACKS.labels(reason=fallback_reason).inc()
        return False
    return True


def use_paged_pallas(count=True):
    """Trace-time paged-attention impl decision shared by the decode
    and prefill ops (ops/nn.py) and the engine's stats/bench reporting.
    ``auto`` prefers the Pallas kernels on a TPU backend (where decode
    is bandwidth-bound on exactly the gather traffic they remove) and
    the XLA gather path elsewhere; ``MXNET_PAGED_ATTN_IMPL=pallas``
    forces the kernels anywhere via interpret mode.  ``count=False``
    suppresses the fallback counter for observer-only calls (stats)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    return choose_impl(
        "MXNET_PAGED_ATTN_IMPL",
        os.environ.get("MXNET_PAGED_ATTN_IMPL", "auto"), "pallas", on_tpu,
        why="backend=%s; auto uses the compiled kernels only on TPU — "
            "force 'pallas' to run them in interpret mode anywhere"
            % jax.default_backend(),
        force_supported=True, fallback_reason="backend", count=count)


def paged_attn_impl():
    """The active paged-attention implementation name ('pallas' or
    'xla') for stats()/bench JSON — no counter side effects."""
    return "pallas" if use_paged_pallas(count=False) else "xla"


def use_layernorm_pallas(axis_last=True):
    """Impl decision for the fused LayerNorm (+residual) kernel
    (``MXNET_LN_IMPL``): auto = kernel on TPU when normalizing the
    LAST axis (the transformer symbol path), forceable anywhere via
    interpret mode — forcing with a non-last axis still raises, since
    the kernel's row-tile layout only covers ``axis=-1``."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    return choose_impl(
        "MXNET_LN_IMPL",
        os.environ.get("MXNET_LN_IMPL", "auto"), "pallas",
        axis_last and on_tpu,
        why="backend=%s, axis_last=%s; auto uses the compiled kernel "
            "only on TPU with axis=-1 — force 'pallas' to run it in "
            "interpret mode anywhere (axis=-1 still required)"
            % (jax.default_backend(), axis_last),
        force_supported=axis_last, fallback_reason="backend")


def use_q2bit_pallas():
    """Impl decision for the fused 2-bit quantize kernel on the
    kvstore bucket path (``MXNET_Q2BIT_IMPL``): same semantics as the
    paged knob — auto = kernel on TPU, forceable anywhere (interpret)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    return choose_impl(
        "MXNET_Q2BIT_IMPL",
        os.environ.get("MXNET_Q2BIT_IMPL", "auto"), "pallas", on_tpu,
        why="backend=%s; auto uses the compiled kernel only on TPU — "
            "force 'pallas' to run it in interpret mode anywhere"
            % jax.default_backend(),
        force_supported=True, fallback_reason="backend")
