"""Paged-KV-cache attention kernels (docs/KERNELS.md).

The XLA reference path in ops/nn.py gathers the **entire addressable
context** per slot per decode step (``jnp.take`` over the block table
-> a ``(C, M*bs, H, D)`` temp) — O(cache) HBM traffic per token.  The
kernels here walk the block table INSIDE the kernel via Pallas scalar
prefetch: each grid step's ``BlockSpec`` index map reads the prefetched
table to pull exactly one cache block into VMEM, an online softmax
accumulates across blocks, and no gathered context tensor ever exists.

* :func:`paged_decode_attend` — one token per slot against its cache
  rows ``[0, pos]``; inactive slots (``pos < 0``) emit zeros (the XLA
  path emits don't-care values there; the engine masks both).
* :func:`paged_prefill_attend` — causal MHA over the padded prompt
  batch with the K/V cache scatter FUSED into the same kernel: per
  (row, cache-block) grid step the kernel writes the block's new rows
  (masked to ``< length``) through an input/output-aliased cache.
  Grid steps past a row's last real block are clamped onto that block
  (an idempotent duplicate write), so padded table entries are never
  dereferenced — the in-kernel equivalent of the XLA path's ``nb*bs``
  OOB-drop sentinel.

* :func:`paged_chunk_prefill_attend` — the chunked-prefill variant:
  a K-token chunk of ONE prompt attends causally against the full
  context so far (prior chunks read back from the paged cache, the
  chunk's own rows merged in-kernel), with the chunk's K/V scatter
  fused through the same clamp-onto-last-real-block discipline but
  addressed at an absolute ``start`` offset into an EXISTING cache.

Off-TPU the wrappers run ``interpret=True`` so CPU tier-1 executes the
exact kernel logic against the XLA reference (parity pinned at
rtol<=2e-5 f32 in tests/test_pallas.py).  Block-size tuning notes live
in docs/KERNELS.md.
"""
import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:               # pragma: no cover — the pinned
    pl = pltpu = None           # toolchain always ships pallas

from .. import telemetry as _telemetry
from ..telemetry.registry import RETRACE_SUPPRESS
from .dispatch import PALLAS_LAUNCHES, PALLAS_RETRACES

# kernel (re)builds land in a vital counter like every other trace
# site; wrappers note() at build time (trace time of the enclosing
# program), so a kernel being reconstructed per call is visible
_SITE = _telemetry.RetraceSite(PALLAS_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="pallas")
_note_kernel_build = _SITE.note


def _interpret_default(interpret):
    if interpret is not None:
        return bool(interpret)
    return jax.default_backend() != "tpu"


def _count_launch(kernel):
    _note_kernel_build()
    if not RETRACE_SUPPRESS.on:   # skip program-registry re-lowers
        PALLAS_LAUNCHES.labels(kernel=kernel).inc()


# ----------------------------------------------------------------------
# decode: one token per slot, online softmax over the slot's blocks
# ----------------------------------------------------------------------
def _paged_decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, bs, scale):
    c = pl.program_id(0)
    m = pl.program_id(1)
    pos = pos_ref[c]

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks past the slot's position are never loaded into the
    # softmax — the online-softmax state simply skips them (and an
    # inactive slot, pos < 0, skips every block)
    @pl.when(jnp.logical_and(pos >= 0, m * bs <= pos))
    def _block():
        q = q_ref[0].astype(jnp.float32)              # (H, D)
        k = k_ref[0].astype(jnp.float32)              # (bs, H, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale   # (H, bs)
        j = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + m * bs
        s = jnp.where(j <= pos, s, -jnp.inf)
        m_prev = m_ref[...]                           # (H, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # (H, bs)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)       # (H, D)
        m_ref[...] = m_new

    @pl.when(m == pl.num_programs(1) - 1)
    def _emit():
        l = l_ref[...]
        o = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = o.astype(o_ref.dtype)


def paged_decode_attend(q, k_cache, v_cache, block_table, positions, *,
                        scale, interpret=None):
    """Paged decode attention: ``q (C, H, D)`` against cache rows
    ``[0, positions[c]]`` addressed through ``block_table (C, M)``;
    ``k_cache/v_cache (num_blocks, block_size, H, D)`` already hold
    the current token's K/V (the scatter is XLA-side in ops/nn.py,
    shared with the reference path).  Returns ``(C, H, D)``; inactive
    slots (``positions < 0``) return zeros.  The grid is (slot, table
    block); each step's index map reads the scalar-prefetched table so
    exactly one cache block streams through VMEM per step."""
    C, H, D = q.shape
    bs = k_cache.shape[1]
    M = block_table.shape[1]
    _count_launch("paged_decode_attend")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(C, M),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda c, m, t, p: (c, 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda c, m, t, p: (t[c, m], 0, 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda c, m, t, p: (t[c, m], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda c, m, t, p: (c, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),   # online-softmax acc
            pltpu.VMEM((H, 1), jnp.float32),   # running max
            pltpu.VMEM((H, 1), jnp.float32),   # running denom
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_paged_decode_kernel, bs=bs,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((C, H, D), q.dtype),
        interpret=_interpret_default(interpret),
    )
    return fn(block_table.astype(jnp.int32),
              positions.astype(jnp.int32), q, k_cache, v_cache)


# ----------------------------------------------------------------------
# prefill: causal MHA + the cache scatter fused into one kernel
# ----------------------------------------------------------------------
def _paged_prefill_kernel(table_ref, len_ref, q_ref, k_ref, v_ref,
                          kc_ref, vc_ref, o_ref, ko_ref, vo_ref, *,
                          bs, scale):
    b = pl.program_id(0)
    m = pl.program_id(1)
    L = len_ref[b]

    # causal attention for query rows [m*bs, (m+1)*bs) against the
    # row's full K/V (VMEM-resident: prefill buckets are short)
    q = q_ref[0].astype(jnp.float32)                  # (bs, H, D)
    k = k_ref[0].astype(jnp.float32)                  # (S, H, D)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((1,), (1,))),
        preferred_element_type=jnp.float32) * scale   # (H, bs, S)
    jq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + m * bs
    jk = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(jq >= jk, s, -jnp.inf)
    mx = jnp.max(s, axis=2, keepdims=True)
    p = jnp.exp(s - mx)
    p = p / jnp.sum(p, axis=2, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)           # (H, bs, D)
    o_ref[0] = o.transpose(1, 0, 2).astype(o_ref.dtype)

    # fused scatter: this block's K/V rows into cache block
    # table[b, m], masked to rows < L.  Grid steps PAST the row's last
    # real block (m*bs >= L, where the table holds padding/garbage) are
    # CLAMPED — index maps and this slice both redirect to the last
    # real block, so the step re-emits that block's exact bytes: a
    # duplicate idempotent write instead of a write through an
    # untrusted table entry (the in-kernel analog of the XLA path's
    # nb*bs OOB-drop sentinel, which likewise never dereferences
    # padded entries).
    m_eff = jnp.minimum(m, jnp.maximum(-(-L // bs), 1) - 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (bs, 1, 1), 0) + m_eff * bs
    keep = row < L
    ko_ref[0] = jnp.where(
        keep,
        jax.lax.dynamic_slice_in_dim(k_ref[0], m_eff * bs, bs, 0)
        .astype(ko_ref.dtype), kc_ref[0])
    vo_ref[0] = jnp.where(
        keep,
        jax.lax.dynamic_slice_in_dim(v_ref[0], m_eff * bs, bs, 0)
        .astype(vo_ref.dtype), vc_ref[0])


def paged_prefill_attend(q, k, v, k_cache, v_cache, block_table,
                         lengths, *, scale, interpret=None):
    """Causal MHA over ``q/k/v (B, S, H, D)`` with the scatter of each
    row's first ``lengths[b]`` K/V rows into the paged cache fused into
    the same kernel (the caches are input/output aliased — in-place
    block writes, no whole-cache copy).  Returns
    ``(out (B, S, H, D), new_k_cache, new_v_cache)``.  ``S`` is padded
    up to a block-size multiple internally, so any prefill bucket
    geometry works."""
    B, S, H, D = q.shape
    bs = k_cache.shape[1]
    pad = (-S) % bs
    if pad:
        # padded keys sit at jk >= S: the causal mask keeps them out of
        # every real query row, and `keep` (row >= L) keeps them out of
        # the cache
        zeros = jnp.zeros((B, pad, H, D), q.dtype)
        q = jnp.concatenate([q, zeros], axis=1)
        k = jnp.concatenate([k, zeros.astype(k.dtype)], axis=1)
        v = jnp.concatenate([v, zeros.astype(v.dtype)], axis=1)
    Sp = S + pad
    Mq = Sp // bs
    if block_table.shape[1] < Mq:
        raise ValueError(
            "paged_prefill_attend: block_table width %d < %d blocks "
            "needed for a %d-token prompt at block_size %d"
            % (block_table.shape[1], Mq, S, bs))
    _count_launch("paged_prefill_attend")

    def cache_block(b, m, t, l):
        # clamp to the row's LAST REAL block once m runs past the
        # length: table entries there are padding (the engine leaves
        # zeros) and must never be dereferenced — the kernel re-emits
        # the last real block instead (idempotent duplicate write)
        last = jnp.maximum(-(-l[b] // bs), 1) - 1
        return (t[b, jnp.minimum(m, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Mq),
        in_specs=[
            pl.BlockSpec((1, bs, H, D), lambda b, m, t, l: (b, m, 0, 0)),
            pl.BlockSpec((1, Sp, H, D), lambda b, m, t, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, Sp, H, D), lambda b, m, t, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, D), cache_block),
            pl.BlockSpec((1, bs, H, D), cache_block),
        ],
        out_specs=[
            pl.BlockSpec((1, bs, H, D), lambda b, m, t, l: (b, m, 0, 0)),
            pl.BlockSpec((1, bs, H, D), cache_block),
            pl.BlockSpec((1, bs, H, D), cache_block),
        ],
        scratch_shapes=[],
    )
    fn = pl.pallas_call(
        functools.partial(_paged_prefill_kernel, bs=bs,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Sp, H, D), q.dtype),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        # cache in -> cache out: in-place block writes, no cache copy
        input_output_aliases={5: 1, 6: 2},
        interpret=_interpret_default(interpret),
    )
    out, ko, vo = fn(block_table.astype(jnp.int32),
                     lengths.astype(jnp.int32), q, k, v,
                     k_cache, v_cache)
    return out[:, :S], ko, vo


# ----------------------------------------------------------------------
# chunked prefill: one prompt chunk against an EXISTING cache prefix
# ----------------------------------------------------------------------
def _paged_chunk_prefill_kernel(table_ref, start_ref, len_ref, q_ref,
                                kpad_ref, vpad_ref, kc_ref, vc_ref,
                                o_ref, ko_ref, vo_ref, acc_ref, m_ref,
                                l_ref, *, bs, scale):
    b = pl.program_id(0)
    m = pl.program_id(1)
    st = start_ref[b]
    L = len_ref[b]
    end = st + L
    # blocks holding real context once this chunk lands: [0, nctx)
    nctx = jnp.maximum(-(-end // bs), 1)
    m_eff = jnp.minimum(m, nctx - 1)

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # merge the chunk's rows into this step's cache block: block m_eff
    # holds absolute rows [m_eff*bs, m_eff*bs + bs); rows inside
    # [start, end) come from the chunk (kpad carries bs zero rows on
    # each side so the dynamic slice stays in-bounds even when the
    # chunk straddles a block boundary), every other row keeps its
    # existing cache bytes.  Clamped steps (m >= nctx) re-emit the last
    # real block's exact bytes — the idempotent duplicate write that
    # keeps padded table entries undereferenced.
    row_abs = (jax.lax.broadcasted_iota(jnp.int32, (bs, 1, 1), 0)
               + m_eff * bs)
    in_chunk = jnp.logical_and(row_abs >= st, row_abs < end)
    off = m_eff * bs - st           # chunk-local index of the block's
    kslice = jax.lax.dynamic_slice_in_dim(   # first row (may be < 0)
        kpad_ref[0], off + bs, bs, 0)
    vslice = jax.lax.dynamic_slice_in_dim(vpad_ref[0], off + bs, bs, 0)
    kblk = jnp.where(in_chunk, kslice.astype(kc_ref.dtype), kc_ref[0])
    vblk = jnp.where(in_chunk, vslice.astype(vc_ref.dtype), vc_ref[0])
    ko_ref[0] = kblk
    vo_ref[0] = vblk

    # online softmax over the merged context blocks; clamped steps are
    # skipped so the duplicate write never double-counts a block
    @pl.when(m < nctx)
    def _block():
        q = q_ref[0].astype(jnp.float32)              # (K, H, D)
        kk = kblk.astype(jnp.float32)                 # (bs, H, D)
        vv = vblk.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kk, (((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32) * scale   # (H, K, bs)
        jq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + st
        jk = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) + m * bs
        # causal over the FULL context: prior chunks fully visible,
        # in-chunk keys causally.  Finite fill (not -inf): a later
        # block can be entirely masked for early queries, and
        # exp(m_prev - max(m_prev, -1e30)) must stay 0/1, not NaN.
        s = jnp.where(jk <= jq, s, -1e30)
        m_prev = m_ref[...]                           # (H, K, 1)
        m_new = jnp.maximum(m_prev,
                            jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # (H, K, bs)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=2,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vv, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)       # (H, K, D)
        m_ref[...] = m_new

    @pl.when(m == pl.num_programs(1) - 1)
    def _emit():
        l = l_ref[...]
        o = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = o.transpose(1, 0, 2).astype(o_ref.dtype)


def paged_chunk_prefill_attend(q, k, v, k_cache, v_cache, block_table,
                               start, lengths, *, scale,
                               interpret=None):
    """Chunked prefill attention over an EXISTING cache: the chunk rows
    ``q/k/v (B, K, H, D)`` sit at absolute positions
    ``[start[b], start[b] + lengths[b])`` of their sequences; each
    chunk query attends causally against the full context so far —
    earlier chunks' K/V are streamed back from the paged cache block by
    block, the chunk's own K/V are merged in-kernel before the block is
    both attended and written back through the input/output-aliased
    caches.  Rows past ``lengths[b]`` are padding: never scattered,
    outputs don't-care.  ``lengths[b] == 0`` makes row ``b`` a no-op
    (block 0 is re-emitted byte-identically).  Returns
    ``(out (B, K, H, D), new_k_cache, new_v_cache)``.

    The per-row start/length geometry makes this kernel double as the
    VERIFY step of speculative decoding (docs/DECODE.md): the engine's
    span step batches one draft span per slot — row ``b`` holds a
    slot's last committed token plus its draft, ``start[b]`` its cache
    cursor — so scoring K+1 positions for every slot costs the same
    single launch as one prompt chunk.  Nothing here is spec-specific:
    the span IS a chunk that happens to contain unverified tokens."""
    B, K, H, D = q.shape
    bs = k_cache.shape[1]
    M = block_table.shape[1]
    _count_launch("paged_chunk_prefill_attend")
    zk = jnp.zeros((B, bs, H, D), k.dtype)
    zv = jnp.zeros((B, bs, H, D), v.dtype)
    kpad = jnp.concatenate([zk, k, zk], axis=1)   # (B, K + 2*bs, H, D)
    vpad = jnp.concatenate([zv, v, zv], axis=1)
    Kp = K + 2 * bs

    def cache_block(b, m, t, st, l):
        # same clamp as paged_prefill_attend, but the last real block
        # is start+length blocks in — the chunk extends a live prefix
        last = jnp.maximum(-(-(st[b] + l[b]) // bs), 1) - 1
        return (t[b, jnp.minimum(m, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, M),
        in_specs=[
            pl.BlockSpec((1, K, H, D),
                         lambda b, m, t, st, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, Kp, H, D),
                         lambda b, m, t, st, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, Kp, H, D),
                         lambda b, m, t, st, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, D), cache_block),
            pl.BlockSpec((1, bs, H, D), cache_block),
        ],
        out_specs=[
            pl.BlockSpec((1, K, H, D),
                         lambda b, m, t, st, l: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, D), cache_block),
            pl.BlockSpec((1, bs, H, D), cache_block),
        ],
        scratch_shapes=[
            pltpu.VMEM((H, K, D), jnp.float32),   # online-softmax acc
            pltpu.VMEM((H, K, 1), jnp.float32),   # running max
            pltpu.VMEM((H, K, 1), jnp.float32),   # running denom
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_paged_chunk_prefill_kernel, bs=bs,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, K, H, D), q.dtype),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        # cache in -> cache out: in-place block writes, no cache copy
        # (scalar-prefetch args count: table=0, start=1, len=2, q=3,
        # kpad=4, vpad=5, k_cache=6, v_cache=7)
        input_output_aliases={6: 1, 7: 2},
        interpret=_interpret_default(interpret),
    )
    return fn(block_table.astype(jnp.int32), start.astype(jnp.int32),
              lengths.astype(jnp.int32), q, kpad, vpad,
              k_cache, v_cache)
