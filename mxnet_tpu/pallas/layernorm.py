"""Fused LayerNorm (+ optional residual-add) kernels
(docs/KERNELS.md — the ISSUE 17 registry-ranked kernel).

``telemetry.programs()`` ranks the transformer step's residual ops by
compiler-reported bytes: after attention and the matmuls, the LayerNorm
chain is the top non-matmul traffic — XLA emits mean/variance/normalize
/scale/shift as separate HBM passes plus a fourth for the preceding
residual add.  This kernel computes the whole chain in ONE pass over
VMEM row tiles: each input element is read once and written once
(forward), and the backward kernel fuses dx with the dgamma/dbeta
row-reductions via grid-sequential accumulation.

Contract (shared with the attention/quant kernels):

* dispatch rides ``MXNET_LN_IMPL`` through ``dispatch.choose_impl``
  (``auto`` = compiled kernel on TPU only; force ``pallas`` to run it
  in interpret mode anywhere — how tier-1 pins parity on CPU);
* host wrappers thread ``_count_launch`` so kernel builds land in the
  same retrace/launch witnesses as every other program;
* gradients flow through a ``jax.custom_vjp`` pair, so the symbol
  path's fwd+bwd both stay fused.  Cotangents arriving on the
  mean/inv_std outputs are NOT propagated (ops/nn.py routes here only
  when ``output_mean_var=False``, where they are structurally unused).

Rows are padded to 8-sublane tiles and the feature dim to 128 lanes;
reductions mask the padded lanes, so any (rows, features) geometry
with ``axis=-1`` is supported.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
except Exception:               # pragma: no cover — the pinned
    pl = None                   # toolchain always ships pallas

from .attention import _count_launch, _interpret_default

# one (8, C_pad) f32 row tile per grid step: 8 sublanes is the native
# f32 tile height and a whole (padded) feature row must sit in VMEM for
# the single-pass row reduction
_TILE_ROWS = 8
_LANES = 128


def _ln_fwd_kernel(cols, eps, with_res):
    inv_cols = 1.0 / float(cols)

    def kernel(*refs):
        if with_res:
            x_ref, res_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref = refs
        else:
            x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref = refs
        x = x_ref[...].astype(jnp.float32)
        if with_res:
            x = x + res_ref[...].astype(jnp.float32)
        mask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < cols
        mean = jnp.sum(jnp.where(mask, x, 0.0), axis=1,
                       keepdims=True) * inv_cols
        d = jnp.where(mask, x - mean, 0.0)
        var = jnp.sum(d * d, axis=1, keepdims=True) * inv_cols
        rstd = lax.rsqrt(var + eps)
        g = g_ref[...].astype(jnp.float32)
        b = b_ref[...].astype(jnp.float32)
        o_ref[...] = (d * rstd * g + b).astype(o_ref.dtype)
        mean_ref[...] = jnp.broadcast_to(mean, mean_ref.shape)
        rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)

    return kernel


def _ln_bwd_kernel(cols, with_res):
    inv_cols = 1.0 / float(cols)

    def kernel(x_ref, res_ref, g_ref, mean_ref, rstd_ref, dy_ref,
               dx_ref, dg_ref, db_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dg_ref[...] = jnp.zeros_like(dg_ref)
            db_ref[...] = jnp.zeros_like(db_ref)

        x = x_ref[...].astype(jnp.float32)
        if with_res:
            x = x + res_ref[...].astype(jnp.float32)
        mask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < cols
        mean = mean_ref[...][:, :1]
        rstd = rstd_ref[...][:, :1]
        xhat = jnp.where(mask, (x - mean) * rstd, 0.0)
        dy = jnp.where(mask, dy_ref[...].astype(jnp.float32), 0.0)
        g = g_ref[...].astype(jnp.float32)
        dxhat = dy * g
        m1 = jnp.sum(dxhat, axis=1, keepdims=True) * inv_cols
        m2 = jnp.sum(dxhat * xhat, axis=1, keepdims=True) * inv_cols
        dx = rstd * (dxhat - m1 - xhat * m2)
        dx_ref[...] = jnp.where(mask, dx, 0.0).astype(dx_ref.dtype)
        dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)

    return kernel


def _pad2(a, rows_pad, cols_pad):
    r, c = a.shape
    if r == rows_pad and c == cols_pad:
        return a
    return jnp.pad(a, ((0, rows_pad - r), (0, cols_pad - c)))


def _vec_pad(v, cols_pad):
    v = v.reshape(1, -1)
    if v.shape[1] != cols_pad:
        v = jnp.pad(v, ((0, 0), (0, cols_pad - v.shape[1])))
    return v


def _geometry(rows, cols):
    cols_pad = -(-cols // _LANES) * _LANES
    rows_pad = -(-rows // _TILE_ROWS) * _TILE_ROWS
    return rows_pad, cols_pad


def _ln_forward(eps, interpret, x2d, gamma, beta, residual):
    rows, cols = x2d.shape
    rows_pad, cols_pad = _geometry(rows, cols)
    with_res = residual is not None
    _count_launch("layernorm_fused")
    grid = (rows_pad // _TILE_ROWS,)
    row_spec = pl.BlockSpec((_TILE_ROWS, cols_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, cols_pad), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0))
    in_specs = [row_spec] + ([row_spec] if with_res else []) \
        + [vec_spec, vec_spec]
    fn = pl.pallas_call(
        _ln_fwd_kernel(cols, eps, with_res),
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, cols_pad), x2d.dtype),
            jax.ShapeDtypeStruct((rows_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows_pad, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )
    args = [_pad2(x2d, rows_pad, cols_pad)]
    if with_res:
        args.append(_pad2(residual, rows_pad, cols_pad))
    args += [_vec_pad(gamma, cols_pad), _vec_pad(beta, cols_pad)]
    out, mean, rstd = fn(*args)
    return out[:rows, :cols], mean[:rows, 0], rstd[:rows, 0]


def _ln_backward(eps, interpret, saved, dy):
    x2d, gamma, residual, mean, rstd = saved
    rows, cols = x2d.shape
    rows_pad, cols_pad = _geometry(rows, cols)
    with_res = residual is not None
    _count_launch("layernorm_fused_bwd")
    grid = (rows_pad // _TILE_ROWS,)
    row_spec = pl.BlockSpec((_TILE_ROWS, cols_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, cols_pad), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0))
    fn = pl.pallas_call(
        _ln_bwd_kernel(cols, with_res),
        grid=grid,
        in_specs=[row_spec, row_spec, vec_spec, stat_spec, stat_spec,
                  row_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, cols_pad), x2d.dtype),
            jax.ShapeDtypeStruct((1, cols_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, cols_pad), jnp.float32),
        ],
        interpret=interpret,
    )
    # padded stat rows carry rstd=0 so padded-row dx is exactly zero
    stat = jnp.zeros((rows_pad, _LANES), jnp.float32)
    mean_t = stat.at[:rows, :].set(mean.reshape(-1, 1))
    rstd_t = stat.at[:rows, :].set(rstd.reshape(-1, 1))
    res_t = _pad2(residual, rows_pad, cols_pad) if with_res \
        else jnp.zeros((rows_pad, cols_pad), x2d.dtype)
    dx, dg, db = fn(_pad2(x2d, rows_pad, cols_pad), res_t,
                    _vec_pad(gamma, cols_pad), mean_t, rstd_t,
                    _pad2(dy, rows_pad, cols_pad))
    dx = dx[:rows, :cols]
    dg = dg[0, :cols].astype(gamma.dtype)
    db = db[0, :cols]
    dres = dx if with_res else None
    return dx, dg, db, dres


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _layernorm(eps, interpret, x2d, gamma, beta, residual):
    return _ln_forward(eps, interpret, x2d, gamma, beta, residual)


def _layernorm_fwd(eps, interpret, x2d, gamma, beta, residual):
    out, mean, rstd = _ln_forward(eps, interpret, x2d, gamma, beta,
                                  residual)
    return (out, mean, rstd), (x2d, gamma, residual, mean, rstd)


def _layernorm_bwd_rule(eps, interpret, saved, cts):
    # cts[1]/cts[2] (mean / inv_std cotangents) are structurally unused
    # on the routed path (output_mean_var=False) — not propagated
    dx, dg, db, dres = _ln_backward(eps, interpret, saved, cts[0])
    return dx, dg, db, dres


_layernorm.defvjp(_layernorm_fwd, _layernorm_bwd_rule)


def layernorm_fused(x, gamma, beta, *, residual=None, eps=1e-5,
                    interpret=None):
    """Fused LayerNorm over the LAST axis, optionally fused with a
    preceding residual add (``x + residual`` never materializes in
    HBM).  Returns ``(out, mean, inv_std)`` — out in ``x.dtype``,
    stats in f32 with ``x.shape[:-1]`` — matching the XLA reference in
    ops/nn.py ``layer_norm`` bit-for-parity within FMA-contraction
    ulps.  Differentiable wrt x / gamma / beta / residual through the
    fused backward kernel."""
    cols = x.shape[-1]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, cols)
    r2 = residual.reshape(-1, cols) if residual is not None else None
    out, mean, rstd = _layernorm(float(eps),
                                 bool(_interpret_default(interpret)),
                                 x2, gamma.reshape(-1), beta.reshape(-1),
                                 r2)
    return (out.reshape(x.shape), mean.reshape(lead),
            rstd.reshape(lead))
