"""mx.pallas — in-repo Pallas kernel library (docs/KERNELS.md).

The paper names "NN ops lowering to XLA/Pallas" as a first-class goal;
this package holds the custom TPU kernels behind the framework's
`*_IMPL` knobs:

* :mod:`attention` — paged-KV-cache decode attention (walks the block
  table inside the kernel, online softmax, no materialized context
  tensor) and the prefill variant with the cache scatter fused into
  the same kernel.
* :mod:`quant` — fused 2-bit quantize (error-feedback residual) for
  the kvstore bucket path.
* :mod:`layernorm` — fused LayerNorm (+ optional residual add)
  forward/backward for the transformer symbol path (``MXNET_LN_IMPL``;
  the ISSUE 17 registry-ranked kernel).
* :mod:`dispatch` — the one ``auto|<kernel>|xla`` selection contract
  shared by every kernel knob (``MXNET_ATTN_IMPL``,
  ``MXNET_PAGED_ATTN_IMPL``, ``MXNET_Q2BIT_IMPL``, ``MXNET_LN_IMPL``),
  plus the ``pallas_kernel_launches`` / ``pallas_fallbacks``
  witnesses.

Every kernel runs under ``interpret=True`` off-TPU, so the CPU
container and tier-1 exercise the exact kernel code paths against the
XLA reference paths (the interpret-mode testing convention,
docs/KERNELS.md).  jax is imported lazily inside the kernel modules'
functions where possible; importing this package does not require a
TPU.
"""
from . import dispatch
from .dispatch import (PALLAS_FALLBACKS, PALLAS_LAUNCHES, choose_impl,
                       paged_attn_impl, use_layernorm_pallas,
                       use_paged_pallas, use_q2bit_pallas)
from . import attention
from .attention import (paged_chunk_prefill_attend, paged_decode_attend,
                        paged_prefill_attend)
from . import quant
from .quant import two_bit_quantize_fused
from . import layernorm
from .layernorm import layernorm_fused

__all__ = [
    "attention", "dispatch", "quant", "layernorm",
    "choose_impl", "paged_attn_impl", "use_paged_pallas",
    "use_q2bit_pallas", "use_layernorm_pallas",
    "paged_chunk_prefill_attend",
    "paged_decode_attend", "paged_prefill_attend",
    "two_bit_quantize_fused", "layernorm_fused",
    "PALLAS_FALLBACKS", "PALLAS_LAUNCHES",
]
