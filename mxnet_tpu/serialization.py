"""NDArray file serialization.

Reference parity: NDArray::Save/Load (src/ndarray/ndarray.cc) used by
mx.nd.save/load and checkpointing. The container here is NumPy ``.npz``
(self-describing, portable) rather than the reference's dmlc binary stream;
the API-level semantics (list or str-keyed dict of NDArrays, ``arg:``/
``aux:`` prefixes for checkpoints) are identical.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["save_ndarray_file", "load_ndarray_file", "load_ndarray_bytes"]

_LIST_KEY = "__mx_list_%d"


def save_ndarray_file(fname, data):
    from .ndarray.ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        arrays = {_LIST_KEY % i: d.asnumpy() for i, d in enumerate(data)}
    elif isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
    else:
        raise TypeError("save expects NDArray, list, or dict")
    with open(fname, "wb") as f:
        _np.savez(f, **arrays)


def load_ndarray_file(fname):
    from .ndarray.ndarray import array
    with _np.load(fname, allow_pickle=False) as npz:
        keys = list(npz.keys())
        if keys and all(k.startswith("__mx_list_") for k in keys):
            out = [None] * len(keys)
            for k in keys:
                out[int(k[len("__mx_list_"):])] = array(npz[k])
            return out
        return {k: array(npz[k]) for k in keys}


def load_ndarray_bytes(buf):
    """Load a serialized params blob from memory (the reference C predict
    API takes the params file as a buffer; same .npz container here,
    same list/dict semantics as load_ndarray_file)."""
    import io as _io
    return load_ndarray_file(_io.BytesIO(buf))
