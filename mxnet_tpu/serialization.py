"""NDArray file serialization — reference binary format + npz.

Reference parity: NDArray::Save/Load (src/ndarray/ndarray.cc:1537-1760)
used by mx.nd.save/load and checkpointing. ``save_ndarray_file`` writes
the reference's exact dmlc binary stream (list magic 0x112, per-array
V2 magic 0xF993fac9, int64 TShape, cpu Context, mshadow type flags,
row_sparse/CSR aux blocks), so ``.params`` files round-trip with real
MXNet 1.x artifacts in both directions. ``load_ndarray_file`` sniffs the
container: reference binary (including the V1 0xF993fac8 and pre-V1
"magic is ndim" legacy layouts, ndarray.cc:1603-1645) or the ``.npz``
container earlier versions of this package wrote.
"""
from __future__ import annotations

import struct

import numpy as _np

__all__ = ["save_ndarray_file", "load_ndarray_file", "load_ndarray_bytes"]

_LIST_KEY = "__mx_list_%d"

_NDLIST_MAGIC = 0x112                 # kMXAPINDArrayListMagic
_ND_V2_MAGIC = 0xF993FAC9             # NDARRAY_V2_MAGIC (storage types)
_ND_V1_MAGIC = 0xF993FAC8             # NDARRAY_V1_MAGIC (int64 TShape)

# mshadow type flags (3rdparty mshadow base.h, used by ndarray.cc Save)
_FLAG_OF_DTYPE = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                  "int32": 4, "int8": 5, "int64": 6}
_DTYPE_OF_FLAG = {v: k for k, v in _FLAG_OF_DTYPE.items()}

_STYPE_DEFAULT, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2


def _write_shape(f, shape):
    f.write(struct.pack("<I", len(shape)))
    f.write(_np.asarray(shape, dtype="<i8").tobytes())


def _write_dense(f, arr):
    arr = _np.ascontiguousarray(arr)
    if str(arr.dtype) not in _FLAG_OF_DTYPE:
        arr = arr.astype("float32")
    if arr.ndim == 0:
        # the reference format has NO 0-d arrays: an ndim-0 record means
        # an EMPTY placeholder NDArray and carries no context/dtype/
        # payload (ndarray.cc NDArray::Load) — writing one here would
        # desync the stream. Scalars save as shape (1,) like 1.x did.
        arr = arr.reshape(1)
    f.write(struct.pack("<I", _ND_V2_MAGIC))
    f.write(struct.pack("<i", _STYPE_DEFAULT))
    _write_shape(f, arr.shape)
    f.write(struct.pack("<ii", 1, 0))          # Context: kCPU(=1), dev 0
    f.write(struct.pack("<i", _FLAG_OF_DTYPE[str(arr.dtype)]))
    f.write(arr.tobytes())


def _write_row_sparse(f, nd):
    """nd is an mxnet_tpu row_sparse NDArray (data + int64 indices)."""
    values = _np.ascontiguousarray(nd.data.asnumpy())
    idx = _np.ascontiguousarray(nd.indices.asnumpy().astype("int64"))
    f.write(struct.pack("<I", _ND_V2_MAGIC))
    f.write(struct.pack("<i", _STYPE_ROW_SPARSE))
    _write_shape(f, values.shape)              # storage shape
    _write_shape(f, nd.shape)
    f.write(struct.pack("<ii", 1, 0))
    f.write(struct.pack("<i", _FLAG_OF_DTYPE[str(values.dtype)]))
    f.write(struct.pack("<i", _FLAG_OF_DTYPE["int64"]))   # aux type kIdx
    _write_shape(f, idx.shape)
    f.write(values.tobytes())
    f.write(idx.tobytes())


def _write_csr(f, nd):
    values = _np.ascontiguousarray(nd.data.asnumpy())
    indptr = _np.ascontiguousarray(nd.indptr.asnumpy().astype("int64"))
    indices = _np.ascontiguousarray(nd.indices.asnumpy().astype("int64"))
    f.write(struct.pack("<I", _ND_V2_MAGIC))
    f.write(struct.pack("<i", _STYPE_CSR))
    _write_shape(f, values.shape)
    _write_shape(f, nd.shape)
    f.write(struct.pack("<ii", 1, 0))
    f.write(struct.pack("<i", _FLAG_OF_DTYPE[str(values.dtype)]))
    f.write(struct.pack("<i", _FLAG_OF_DTYPE["int64"]))   # kIndPtr
    _write_shape(f, indptr.shape)
    f.write(struct.pack("<i", _FLAG_OF_DTYPE["int64"]))   # kIdx
    _write_shape(f, indices.shape)
    f.write(values.tobytes())
    f.write(indptr.tobytes())
    f.write(indices.tobytes())


def _write_ndarray(f, nd):
    stype = getattr(nd, "stype", "default")
    if stype == "row_sparse":
        _write_row_sparse(f, nd)
    elif stype == "csr":
        _write_csr(f, nd)
    else:
        _write_dense(f, nd.asnumpy())


def save_ndarray_file(fname, data, fmt="mxnet"):
    """Save NDArray / list / str-keyed dict. ``fmt='mxnet'`` (default)
    writes the reference dmlc binary; ``fmt='npz'`` the numpy container.
    Arrays whose dtype has no mshadow flag (bfloat16 — MXNet 1.x
    predates it) force the npz container so the dtype round-trips
    exactly instead of being silently cast to float32."""
    from .ndarray.ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        keys, arrays = [], list(data)
    elif isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        raise TypeError("save expects NDArray, list, or dict")

    if fmt == "mxnet":
        stypes = [getattr(a, "stype", "default") for a in arrays]
        needs_npz = False
        for a, stype in zip(arrays, stypes):
            payload_dtype = str(a.dtype if stype == "default"
                                else a.data.dtype)
            if payload_dtype in _FLAG_OF_DTYPE:
                continue
            if stype == "default":
                needs_npz = True
            else:
                # npz fallback densifies, silently changing stype — refuse.
                raise ValueError(
                    "cannot save %s NDArray with dtype %s in fmt='mxnet': "
                    "MXNet 1.x has no mshadow flag for it and the npz "
                    "fallback would densify the array; cast to float32 "
                    "(nd.astype) or save the components separately"
                    % (stype, payload_dtype))
        if needs_npz:
            if any(s != "default" for s in stypes):
                # a bf16 dense array must not silently densify a sparse
                # array that happens to ride in the same file
                raise ValueError(
                    "cannot save sparse NDArrays together with a dtype "
                    "that forces the npz fallback (npz would densify "
                    "them); save them in separate files or cast the "
                    "dense array to a flagged dtype")
            fmt = "npz"

    if fmt == "npz":
        raw = ({k: v.asnumpy() for k, v in zip(keys, arrays)} if keys
               else {_LIST_KEY % i: d.asnumpy()
                     for i, d in enumerate(arrays)})
        named = {}
        for k, a in raw.items():
            if str(a.dtype) == "bfloat16":
                # npz has no bf16 descr: store the bits, mark the key
                named["__bf16__" + k] = a.view(_np.uint16)
            else:
                named[k] = a
        with open(fname, "wb") as f:
            _np.savez(f, **named)
        return

    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _NDLIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for nd in arrays:
            _write_ndarray(f, nd)
        f.write(struct.pack("<Q", len(keys)))
        for k in keys:
            kb = k.encode("utf-8")
            f.write(struct.pack("<Q", len(kb)))
            f.write(kb)


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, n):
        if self.pos + n > len(self.buf):
            raise ValueError("Invalid NDArray file format (truncated)")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]

    def shape64(self):
        ndim = self.u32()
        return tuple(_np.frombuffer(self.read(8 * ndim), "<i8").tolist())

    def array(self, shape, flag):
        dt = _np.dtype(_DTYPE_OF_FLAG[flag])
        n = 1
        for s in shape:
            n *= int(s)
        return _np.frombuffer(self.read(dt.itemsize * n),
                              dt).reshape(shape).copy()


def _read_ndarray(r):
    """One NDArray from the stream (reference NDArray::Load incl. both
    legacy layouts, ndarray.cc:1650/1619). Returns a host numpy array or
    a ('row_sparse'/'csr', ...) tuple for sparse storage."""
    magic = r.u32()
    if magic == _ND_V2_MAGIC:
        stype = r.i32()
        sshape = r.shape64() if stype != _STYPE_DEFAULT else None
        shape = r.shape64()
        if len(shape) == 0:
            return _np.zeros((), "float32")
        r.i32(); r.i32()                       # Context (ignored: host)
        flag = r.i32()
        if stype == _STYPE_DEFAULT:
            return r.array(shape, flag)
        if stype == _STYPE_ROW_SPARSE:
            idx_flag = r.i32()
            idx_shape = r.shape64()
            values = r.array(sshape, flag)
            idx = r.array(idx_shape, idx_flag)
            return ("row_sparse", shape, values, idx)
        if stype == _STYPE_CSR:
            indptr_flag = r.i32()
            indptr_shape = r.shape64()
            idx_flag = r.i32()
            idx_shape = r.shape64()
            values = r.array(sshape, flag)
            indptr = r.array(indptr_shape, indptr_flag)
            idx = r.array(idx_shape, idx_flag)
            return ("csr", shape, values, indptr, idx)
        raise ValueError("unknown storage type %d" % stype)
    if magic == _ND_V1_MAGIC:
        shape = r.shape64()
    else:
        # pre-V1: the magic word IS ndim, with uint32 dims following
        ndim = magic
        if ndim > 32:
            raise ValueError("Invalid NDArray file format (bad magic)")
        shape = tuple(_np.frombuffer(r.read(4 * ndim), "<u4").tolist())
    if len(shape) == 0:
        return _np.zeros((), "float32")
    r.i32(); r.i32()                           # Context
    flag = r.i32()
    return r.array(shape, flag)


def _load_reference_binary(buf):
    from .ndarray.ndarray import array
    from .ndarray import sparse as _sp
    r = _Reader(buf)
    header, _reserved = r.u64(), r.u64()
    if header != _NDLIST_MAGIC:
        raise ValueError("Invalid NDArray file format (bad list magic)")
    n = r.u64()
    raw = [_read_ndarray(r) for _ in range(n)]
    nkeys = r.u64()
    keys = [r.read(r.u64()).decode("utf-8") for _ in range(nkeys)]

    def wrap(x):
        if isinstance(x, tuple) and x and x[0] == "row_sparse":
            _, shape, values, idx = x
            return _sp.row_sparse_array((values, idx), shape=shape,
                                        dtype=str(values.dtype))
        if isinstance(x, tuple) and x and x[0] == "csr":
            _, shape, values, indptr, idx = x
            return _sp.csr_matrix((values, idx, indptr), shape=shape,
                                  dtype=str(values.dtype))
        return array(x)

    out = [wrap(x) for x in raw]
    if nkeys == 0:
        return out
    if nkeys != n:
        raise ValueError("Invalid NDArray file format (key count)")
    return dict(zip(keys, out))


def _load_npz(fobj):
    from .ndarray.ndarray import array

    def _decode(key, a):
        if key.startswith("__bf16__"):
            import ml_dtypes
            return key[len("__bf16__"):], array(a.view(ml_dtypes.bfloat16))
        return key, array(a)

    with _np.load(fobj, allow_pickle=False) as npz:
        decoded = dict(_decode(k, npz[k]) for k in npz.keys())
        keys = list(decoded)
        if keys and all(k.startswith("__mx_list_") for k in keys):
            out = [None] * len(keys)
            for k in keys:
                out[int(k[len("__mx_list_"):])] = decoded[k]
            return out
        return decoded


def load_ndarray_file(fname):
    if hasattr(fname, "read"):
        return load_ndarray_bytes(fname.read())
    with open(fname, "rb") as f:
        head = f.read(8)
        f.seek(0)
        if len(head) >= 8 and struct.unpack("<Q", head)[0] == _NDLIST_MAGIC:
            return _load_reference_binary(f.read())
        return _load_npz(f)


def load_ndarray_bytes(buf):
    """Load a serialized params blob from memory (the reference C predict
    API takes the params file as a buffer). Accepts the reference dmlc
    binary or the npz container."""
    import io as _io
    if len(buf) >= 8 and struct.unpack("<Q", buf[:8])[0] == _NDLIST_MAGIC:
        return _load_reference_binary(buf)
    return _load_npz(_io.BytesIO(buf))
