"""RNN checkpoint helpers (behavioral parity: python/mxnet/rnn/rnn.py:1-121
— unpack weights before save so checkpoints hold readable per-gate arrays,
pack after load so cells/fused ops consume them)."""
from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _cell_list(cells):
    return [cells] if isinstance(cells, BaseRNNCell) else list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save ``prefix-symbol.json`` + ``prefix-NNNN.params`` with every
    cell's weights unpacked to per-gate entries."""
    for cell in _cell_list(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint saved by :func:`save_rnn_checkpoint`, re-packing
    per-gate entries into each cell's stacked/fused layout."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _cell_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback version of :func:`save_rnn_checkpoint`."""
    stride = max(int(period), 1)

    def _on_epoch_end(epoch, sym, arg, aux):
        done = epoch + 1
        if done % stride == 0:
            save_rnn_checkpoint(cells, prefix, done, sym, arg, aux)
    return _on_epoch_end
