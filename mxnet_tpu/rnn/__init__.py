"""mx.rnn — symbol-side RNN utilities (reference python/mxnet/rnn/).

The reference package carries symbol RNN cells plus BucketSentenceIter.
Cells live in ``mx.gluon.rnn`` here (the imperative-first home); the
symbol path uses the fused ``sym.RNN`` op directly (ops/rnn.py — one
lax.scan per graph, the cuDNN-RNN analog). This package provides the
data-side parity surface: BucketSentenceIter and encode_sentences.
"""
from .io import BucketSentenceIter, encode_sentences

__all__ = ["BucketSentenceIter", "encode_sentences"]
