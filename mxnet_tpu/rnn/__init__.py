"""mx.rnn — symbol-side RNN toolkit (reference python/mxnet/rnn/).

Cells: step-composable symbolic cells + combinators (rnn_cell.py), the
fused ``FusedRNNCell`` over ``sym.RNN`` (one lax.scan XLA while-loop),
and the pack/unpack weight bridge between the two.  Data: the bucketing
sentence iterator.  Checkpoints: per-gate save/load helpers (rnn.py).
"""
from .io import BucketSentenceIter, encode_sentences
from .rnn import do_rnn_checkpoint, load_rnn_checkpoint, save_rnn_checkpoint
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams, SequentialRNNCell,
                       ZoneoutCell)

__all__ = ["BucketSentenceIter", "encode_sentences",
           "save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint",
           "RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]
