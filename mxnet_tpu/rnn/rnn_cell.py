"""Symbolic RNN cell zoo: step-composable cells, stacking/bidirectional/
modifier combinators, and the fused<->unfused weight bridge.

API parity: python/mxnet/rnn/rnn_cell.py:1-1436 (same classes, same
parameter names ``{prefix}i2h_weight``..., same per-step op naming
``{prefix}t{N}_``, same cuDNN gate orders — LSTM [i,f,c,o], GRU [r,z,o] —
and the same FusedRNNCell packed-vector layout, so ``unfuse()``/
``pack_weights``/``unpack_weights`` round-trip checkpoints bit-exactly
against the fused ``sym.RNN`` op).  Implementation is re-derived: cells
share a ``_gate_transform`` helper for the i2h/h2h projections, packing
walks one declarative segment table (``_fused_segments``) instead of
hand-maintained pointer arithmetic in four loops, and combinators hold a
``_cells`` list with helpers over it.

On TPU, an unrolled cell graph compiles to one XLA program — the fused
``sym.RNN`` op (one ``lax.scan``) is usually faster for long sequences;
this zoo exists for cell-level composition (residual/zoneout/custom
wiring) and reference-checkpoint interop.
"""
from __future__ import annotations

from .. import initializer as init
from .. import ndarray
from .. import symbol

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]

_FUSED_GATES = {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"], "gru": ["_r", "_z", "_o"]}


def _seq_to_symbol(steps, axis):
    """[per-step 2D symbols] -> one (.., T, ..) symbol on ``axis``."""
    expanded = [symbol.expand_dims(s, axis=axis) for s in steps]
    return symbol.Concat(*expanded, dim=axis)


def _symbol_to_seq(seq, axis, length):
    """One stacked symbol -> list of per-step 2D symbols."""
    return list(symbol.split(seq, axis=axis, num_outputs=length,
                             squeeze_axis=1))


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Coerce ``inputs`` between list-of-steps and single-symbol forms
    (reference rnn_cell.py:51 semantics, incl. the merge=None passthrough)."""
    if inputs is None:
        raise ValueError("unroll(inputs=None) is not supported. Create "
                         "input variables outside unroll.")
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            if len(inputs.list_outputs()) != 1:
                raise ValueError(
                    "unroll doesn't allow grouped symbol as input. Convert "
                    "to list with list(inputs) first or let unroll split.")
            inputs = _symbol_to_seq(inputs, in_axis, length)
    else:
        if length is not None and len(inputs) != length:
            raise ValueError(f"len(inputs)={len(inputs)} != length={length}")
        if merge is True:
            inputs = _seq_to_symbol(list(inputs), axis)
            in_axis = axis
    if isinstance(inputs, symbol.Symbol) and axis != in_axis:
        inputs = symbol.swapaxes(inputs, dim0=axis, dim1=in_axis)
    return inputs, axis


class RNNParams:
    """Shared Variable container: cells co-owning one RNNParams share
    weights by name."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = symbol.Variable(full, **kwargs)
        return self._params[full]


class BaseRNNCell:
    """One-step recurrence builder.  ``cell(step_input, states)`` appends
    one time step to the graph; ``unroll`` loops it; combinators compose
    cells.  Subclasses define ``state_info``, ``_gate_names`` and the step
    itself."""

    def __init__(self, prefix="", params=None):
        self._own_params = params is None
        self._prefix = prefix
        self._params = params if params is not None else RNNParams(prefix)
        self._modified = False
        self.reset()

    # -- bookkeeping ----------------------------------------------------
    def reset(self):
        """Forget step counters so the cell can build a fresh graph."""
        self._init_counter = -1
        self._counter = -1
        for child in getattr(self, "_cells", ()):
            child.reset()

    def _step_name(self):
        """Advance the step counter and return this step's op-name stem."""
        self._counter += 1
        return f"{self._prefix}t{self._counter}_"

    @property
    def params(self):
        self._own_params = False
        return self._params

    # -- state ----------------------------------------------------------
    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Starting states (zeros by default; pass ``symbol.Variable`` to
        feed them as graph inputs)."""
        if self._modified:
            raise RuntimeError(
                "After applying modifier cells (e.g. DropoutCell) the base "
                "cell cannot be called directly. Call the modifier cell "
                "instead.")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            call_kwargs = dict(kwargs)
            if info is not None:
                call_kwargs.update(info)
            call_kwargs.pop("__layout__", None)
            if func is not symbol.Variable and "shape" in call_kwargs:
                # The reference writes 0 for the unknown batch dim and lets
                # its bidirectional shape unification resolve it.  Our
                # inference is forward-only, so default states are built
                # batch-1 and broadcast against the data inside the graph
                # (zeros broadcast == zeros of the full batch).
                call_kwargs["shape"] = tuple(
                    d if d else 1 for d in call_kwargs["shape"])
            states.append(func(
                name=f"{self._prefix}begin_state_{self._init_counter}",
                **call_kwargs))
        return states

    def __call__(self, inputs, states):
        raise NotImplementedError()

    # -- packed <-> per-gate weight views -------------------------------
    def unpack_weights(self, args):
        """Split this cell's stacked i2h/h2h weight+bias rows into per-gate
        entries (``{prefix}i2h_i_weight``...); non-gated cells no-op."""
        out = dict(args)
        gates = self._gate_names
        if not gates:
            return out
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            stacked_w = out.pop(f"{self._prefix}{group}_weight")
            stacked_b = out.pop(f"{self._prefix}{group}_bias")
            for row, gate in enumerate(gates):
                out[f"{self._prefix}{group}{gate}_weight"] = \
                    stacked_w[row * h:(row + 1) * h].copy()
                out[f"{self._prefix}{group}{gate}_bias"] = \
                    stacked_b[row * h:(row + 1) * h].copy()
        return out

    def pack_weights(self, args):
        """Inverse of :meth:`unpack_weights`."""
        out = dict(args)
        gates = self._gate_names
        if not gates:
            return out
        for group in ("i2h", "h2h"):
            rows_w, rows_b = [], []
            for gate in gates:
                rows_w.append(out.pop(f"{self._prefix}{group}{gate}_weight"))
                rows_b.append(out.pop(f"{self._prefix}{group}{gate}_bias"))
            out[f"{self._prefix}{group}_weight"] = ndarray.concatenate(rows_w)
            out[f"{self._prefix}{group}_bias"] = ndarray.concatenate(rows_b)
        return out

    # -- unrolling ------------------------------------------------------
    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Build ``length`` chained steps.  Returns (outputs, states);
        ``merge_outputs`` True gives one stacked symbol, False a list,
        None whichever form fell out naturally."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        step_outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            step_outputs.append(out)
        outputs, _ = _normalize_sequence(length, step_outputs, layout,
                                         merge_outputs)
        return outputs, states

    @staticmethod
    def _activate(data, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(data, act_type=activation, **kwargs)
        return activation(data, **kwargs)


class _GatedCell(BaseRNNCell):
    """Shared machinery for the three concrete cells: the i2h/h2h
    parameter quad and the fused projection of one step's input+state."""

    def __init__(self, num_hidden, prefix, params, i2h_bias_init=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get("i2h_bias", **(
            {"init": i2h_bias_init} if i2h_bias_init is not None else {}))
        self._hB = self.params.get("h2h_bias")

    def _project(self, name, inputs, state, width_mult):
        """i2h and h2h FullyConnected for one step (both land on the MXU)."""
        wide = self._num_hidden * width_mult
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB, num_hidden=wide,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(data=state, weight=self._hW,
                                    bias=self._hB, num_hidden=wide,
                                    name=f"{name}h2h")
        return i2h, h2h

    def _single_state_info(self, count):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}
                for _ in range(count)]


class RNNCell(_GatedCell):
    """Elman cell: out = act(W_i x + W_h h + b)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(num_hidden, prefix, params)
        self._activation = activation

    @property
    def state_info(self):
        return self._single_state_info(1)

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._project(name, inputs, states[0], 1)
        output = self._activate(i2h + h2h, self._activation,
                                name=f"{name}out")
        return output, [output]


class LSTMCell(_GatedCell):
    """LSTM with cuDNN gate order [i, f, c, o]; ``forget_bias`` seeds the
    forget-gate slice of i2h_bias (Jozefowicz et al. 2015)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(num_hidden, prefix, params,
                         i2h_bias_init=init.LSTMBias(forget_bias=forget_bias))

    @property
    def state_info(self):
        return self._single_state_info(2)

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        name = self._step_name()
        i2h, h2h = self._project(name, inputs, states[0], 4)
        gates = symbol.SliceChannel(i2h + h2h, num_outputs=4,
                                    name=f"{name}slice")
        gate_i = symbol.Activation(gates[0], act_type="sigmoid",
                                   name=f"{name}i")
        gate_f = symbol.Activation(gates[1], act_type="sigmoid",
                                   name=f"{name}f")
        cand = symbol.Activation(gates[2], act_type="tanh",
                                 name=f"{name}c")
        gate_o = symbol.Activation(gates[3], act_type="sigmoid",
                                   name=f"{name}o")
        next_c = symbol._plus(gate_f * states[1], gate_i * cand,
                                        name=f"{name}state")
        next_h = symbol._mul(
            gate_o, symbol.Activation(next_c, act_type="tanh"),
            name=f"{name}out")
        return next_h, [next_h, next_c]


class GRUCell(_GatedCell):
    """GRU, cuDNN variant (reset gate applied to the h2h candidate
    projection); gate order [r, z, o]."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(num_hidden, prefix, params)

    @property
    def state_info(self):
        return self._single_state_info(1)

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        name = self._step_name()
        prev = states[0]
        i2h, h2h = self._project(name + "_", inputs, prev, 3)
        i2h_r, i2h_z, i2h_n = symbol.SliceChannel(
            i2h, num_outputs=3, name=f"{name}_i2h_slice")
        h2h_r, h2h_z, h2h_n = symbol.SliceChannel(
            h2h, num_outputs=3, name=f"{name}_h2h_slice")
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                  name=f"{name}_r_act")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                   name=f"{name}_z_act")
        cand = symbol.Activation(i2h_n + reset * h2h_n, act_type="tanh",
                                 name=f"{name}_h_act")
        next_h = symbol._plus((1. - update) * cand, update * prev,
                                        name=f"{name}out")
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """All layers/steps in ONE op: wraps the fused ``sym.RNN``
    (ops/rnn.py — a single ``lax.scan`` XLA while-loop; the cuDNN-RNN
    analog).  Weights live in one packed flat vector whose layout matches
    the reference/cuDNN convention — see ``_fused_segments``."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        super().__init__(prefix=f"{mode}_" if prefix is None else prefix,
                         params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get(
            "parameters", init=init.FusedRNN(None, num_hidden, num_layers,
                                             mode, bidirectional,
                                             forget_bias))

    @property
    def state_info(self):
        depth = len(self._directions) * self._num_layers
        arity = 2 if self._mode == "lstm" else 1
        return [{"shape": (depth, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(arity)]

    @property
    def _gate_names(self):
        return _FUSED_GATES[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    # -- packed layout --------------------------------------------------
    def _fused_segments(self, num_input, h):
        """Yield (param_name, flat_size, view_shape) in packed order: all
        weights layer-major/direction-major (i2h rows per gate, then h2h),
        then all biases in the same order — the cuDNN flat layout."""
        b = len(self._directions)
        for section in ("weight", "bias"):
            for layer in range(self._num_layers):
                width_in = num_input if layer == 0 else b * h
                for d in self._directions:
                    for group, width in (("i2h", width_in), ("h2h", h)):
                        for gate in self._gate_names:
                            name = (f"{self._prefix}{d}{layer}_"
                                    f"{group}{gate}_{section}")
                            if section == "weight":
                                yield name, h * width, (h, width)
                            else:
                                yield name, h, (h,)

    def _slice_weights(self, arr, li, lh):
        """Views of the packed vector, keyed by per-gate param name."""
        views, p = {}, 0
        for name, size, shape in self._fused_segments(li, lh):
            views[name] = arr[p:p + size].reshape(shape)
            p += size
        if p != arr.size:
            raise ValueError("Invalid parameters size for FusedRNNCell")
        return views

    def _infer_num_input(self, packed_size):
        b, m, h = len(self._directions), self._num_gates, self._num_hidden
        return (packed_size // b // h // m
                - (self._num_layers - 1) * (h + b * h + 2) - h - 2)

    def unpack_weights(self, args):
        out = dict(args)
        packed = out.pop(self._parameter.name)
        views = self._slice_weights(
            packed, self._infer_num_input(packed.size), self._num_hidden)
        out.update({name: view.copy() for name, view in views.items()})
        return out

    def pack_weights(self, args):
        out = dict(args)
        first_gate = self._gate_names[0]
        w0 = out[f"{self._prefix}l0_i2h{first_gate}_weight"]
        num_input = w0.shape[1]
        # Build by concatenating the flat segments in packed order (our
        # arrays are immutable JAX buffers — no write-through slice views
        # like the reference's, so assembling beats assigning).
        pieces = [out.pop(name).reshape((-1,))
                  for name, _size, _shape in
                  self._fused_segments(num_input, self._num_hidden)]
        out[self._parameter.name] = ndarray.concatenate(pieces)
        return out

    # -- execution ------------------------------------------------------
    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:
            import warnings
            warnings.warn("NTC layout detected. Consider using TNC for "
                          "FusedRNNCell for faster speed")
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        elif axis != 0:
            raise ValueError(f"Unsupported layout {layout}")
        states = begin_state if begin_state is not None else \
            self.begin_state()
        state_kwargs = {"state": states[0]}
        if self._mode == "lstm":
            state_kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **state_kwargs)
        if not self._get_next_state:
            outputs, states = rnn, []
        else:
            n_states = 2 if self._mode == "lstm" else 1
            outputs = rnn[0]
            states = [rnn[1 + i] for i in range(n_states)]
            for s in states:
                s._set_attr(__layout__="LNC")
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of step cells, named so that
        ``unpack_weights`` of this fused cell loads it directly."""
        factories = {
            "rnn_relu": lambda pfx: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pfx),
            "rnn_tanh": lambda pfx: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pfx),
            "lstm": lambda pfx: LSTMCell(self._num_hidden, prefix=pfx),
            "gru": lambda pfx: GRUCell(self._num_hidden, prefix=pfx)}
        make = factories[self._mode]
        stack = SequentialRNNCell()
        for layer in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make(f"{self._prefix}l{layer}_"),
                    make(f"{self._prefix}r{layer}_"),
                    output_prefix=f"{self._prefix}bi_l{layer}_"))
            else:
                stack.add(make(f"{self._prefix}l{layer}_"))
            if self._dropout > 0 and layer != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{layer}_"))
        return stack


class _CellGroup(BaseRNNCell):
    """Shared plumbing for combinators holding several child cells."""

    def __init__(self, prefix="", params=None):
        super().__init__(prefix=prefix, params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def _adopt(self, cell):
        """Merge param namespaces (shared-params mode requires the child
        to still own its params, as in the reference)."""
        if self._override_cell_params:
            if not cell._own_params:
                raise ValueError(
                    "Either specify params for the container or child "
                    "cells, not both.")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)
        self._cells.append(cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise RuntimeError(
                "After applying modifier cells the base cell cannot be "
                "called directly. Call the modifier cell instead.")
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def _split_states(self, states):
        """Carve the flat state list into per-child slices."""
        out, p = [], 0
        for cell in self._cells:
            n = len(cell.state_info)
            out.append(states[p:p + n])
            p += n
        return out


class SequentialRNNCell(_CellGroup):
    """Stack cells vertically: each child consumes the previous child's
    output at every time step."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)

    def add(self, cell):
        self._adopt(cell)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        for cell, sub in zip(self._cells, self._split_states(states)):
            if isinstance(cell, BidirectionalCell):
                raise ValueError("BidirectionalCell cannot be stepped "
                                 "inside SequentialRNNCell")
            inputs, new = cell(inputs, sub)
            next_states.extend(new)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        states = begin_state if begin_state is not None else \
            self.begin_state()
        per_cell = self._split_states(states)
        next_states = []
        last = len(self._cells) - 1
        for i, (cell, sub) in enumerate(zip(self._cells, per_cell)):
            inputs, new = cell.unroll(
                length, inputs=inputs, begin_state=sub, layout=layout,
                merge_outputs=merge_outputs if i == last else None)
            next_states.extend(new)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout applied to the flowing sequence."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        if not isinstance(dropout, (int, float)):
            raise TypeError("dropout probability must be a number")
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if isinstance(inputs, symbol.Symbol):
            # whole sequence at once: one dropout op covers every step
            return self(inputs, [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(BaseRNNCell):
    """Wrap a cell and alter its step behavior; params stay with the base
    cell, which can no longer be called directly."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, init_sym=symbol.zeros, **kwargs):
        if self._modified:
            raise RuntimeError(
                "After applying modifier cells the base cell cannot be "
                "called directly. Call the modifier cell instead.")
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(init_sym, **kwargs)
        finally:
            self.base_cell._modified = True

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout (Krueger et al.): randomly keep previous outputs/states
    instead of new ones during training."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        if isinstance(base_cell, FusedRNNCell):
            raise TypeError("FusedRNNCell doesn't support zoneout. "
                            "Please unfuse first.")
        if isinstance(base_cell, BidirectionalCell):
            raise TypeError("BidirectionalCell doesn't support zoneout; "
                            "apply ZoneoutCell to the cells underneath.")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)

        def keep_mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev = self.prev_output if self.prev_output is not None \
            else symbol.zeros((1, 1))
        if self.zoneout_outputs != 0.:
            output = symbol.where(keep_mask(self.zoneout_outputs,
                                            next_output),
                                  next_output, prev)
        else:
            output = next_output
        if self.zoneout_states != 0.:
            next_states = [symbol.where(keep_mask(self.zoneout_states, new),
                                        new, old)
                           for new, old in zip(next_states, states)]
        self.prev_output = output
        return output, next_states


class ResidualCell(ModifierCell):
    """output = base_cell(output) + input (GNMT, Wu et al. 2016)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs,
                                     name=f"{output.name}_plus_residual")
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        try:
            outputs, states = self.base_cell.unroll(
                length, inputs=inputs, begin_state=begin_state,
                layout=layout, merge_outputs=merge_outputs)
        finally:
            self.base_cell._modified = True
        if merge_outputs is None:
            merge_outputs = isinstance(outputs, symbol.Symbol)
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(
                outputs, inputs, name=f"{outputs.name}_plus_residual")
        else:
            outputs = [symbol.elemwise_add(o, i,
                                           name=f"{o.name}_plus_residual")
                       for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(_CellGroup):
    """Run one cell forward and one backward over the sequence and
    concatenate their per-step outputs on the feature axis."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        for cell in (l_cell, r_cell):
            self._adopt(cell)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        l_cell, r_cell = self._cells
        l_state_n = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:l_state_n],
            layout=layout, merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[l_state_n:], layout=layout,
            merge_outputs=merge_outputs)

        if merge_outputs is None:
            merge_outputs = (isinstance(l_outputs, symbol.Symbol)
                             and isinstance(r_outputs, symbol.Symbol))
            if not merge_outputs:
                if isinstance(l_outputs, symbol.Symbol):
                    l_outputs = _symbol_to_seq(l_outputs, axis, length)
                if isinstance(r_outputs, symbol.Symbol):
                    r_outputs = _symbol_to_seq(r_outputs, axis, length)

        if merge_outputs:
            l_seq = [l_outputs]
            r_seq = [symbol.reverse(r_outputs, axis=axis)]
        else:
            l_seq = l_outputs
            r_seq = list(reversed(r_outputs))

        outputs = [symbol.Concat(
            l_o, r_o, dim=1 + merge_outputs,
            name=(f"{self._output_prefix}out" if merge_outputs
                  else f"{self._output_prefix}t{i}"))
            for i, (l_o, r_o) in enumerate(zip(l_seq, r_seq))]
        if merge_outputs:
            outputs = outputs[0]
        return outputs, [l_states, r_states]
