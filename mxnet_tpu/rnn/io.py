"""BucketSentenceIter — bucketed language-model batches.

Reference parity: python/mxnet/rnn/io.py (BucketSentenceIter:
sentences assigned to the smallest bucket that fits, padded there,
batched per bucket with ``bucket_key`` so BucketingModule picks the
right executor; labels are the inputs shifted by one).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map token sequences to int ids, building the vocab on the fly
    (reference rnn/io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    raise ValueError("unknown token %s" % word)
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator over encoded sentences (see module docstring)."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = _np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
        buckets = sorted(buckets)
        if not buckets:
            raise MXNetError("no usable buckets for this corpus")

        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = _np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = _np.full((buckets[buck],), invalid_label,
                            _np.dtype(dtype))
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [_np.asarray(x, _np.dtype(dtype)).reshape(-1, b)
                     for x, b in zip(self.data, buckets)]
        if ndiscard:
            import logging
            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.default_bucket_key = max(buckets)

        self.provide_data = [DataDesc(data_name,
                                      (batch_size, self.default_bucket_key),
                                      dtype, layout=layout)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, self.default_bucket_key),
                                       dtype, layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1, batch_size))
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        _pyrandom.shuffle(self.idx)
        for buck in self.data:
            _np.random.shuffle(buck)
        # label = input shifted left by one (next-token prediction)
        self.ndlabel = []
        self.nddata = []
        for buck in self.data:
            label = _np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        from .. import ndarray as nd
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        key = self.buckets[i]
        return DataBatch(data=[nd.array(data)], label=[nd.array(label)],
                         bucket_key=key, pad=0,
                         provide_data=[DataDesc(self.data_name,
                                                (self.batch_size, key),
                                                self.dtype,
                                                layout=self.layout)],
                         provide_label=[DataDesc(self.label_name,
                                                 (self.batch_size, key),
                                                 self.dtype,
                                                 layout=self.layout)])
