"""MXNET_* environment-variable layer.

Reference parity: docs/faq/env_var.md — the reference's only runtime
configuration mechanism is ~40 ``MXNET_*`` env vars read at singleton
init. Most of them tune machinery XLA replaced (engine threads, memory
pools, op bulking); those are **accepted and documented as inert** here
so existing launch scripts keep working. The ones with a real TPU-native
meaning are wired:

- ``MXNET_ENGINE_TYPE=NaiveEngine`` — the reference's synchronous debug
  oracle (src/engine/engine.cc:32): every eager op blocks until the
  device finishes, surfacing async errors at the faulting op instead of
  a later sync point.
- ``MXNET_BACKWARD_DO_MIRROR=1`` — gradient mirroring
  (graph_executor.cc:193): trade compute for activation memory. Maps to
  ``jax.checkpoint`` (rematerialization) around the compiled
  forward when building fused fwd+bwd programs.
- ``MXNET_PROFILER_AUTOSTART=1`` — handled in profiler.py.
- ``MXTPU_NO_NATIVE=1`` — disable the native C++ io library.
"""
from __future__ import annotations

import os

__all__ = ["env_bool", "naive_engine", "backward_do_mirror", "summary"]

# name -> (default, wired?, doc)
_KNOWN = {
    "MXNET_ENGINE_TYPE": ("ThreadedEnginePerDevice", True,
                          "NaiveEngine = synchronous eager ops (debug "
                          "oracle); other values inert (XLA dispatch)"),
    "MXNET_BACKWARD_DO_MIRROR": ("0", True,
                                 "1 = rematerialize forward in fused "
                                 "fwd+bwd programs (jax.checkpoint)"),
    "MXNET_PROFILER_AUTOSTART": ("0", True, "1 = start mx.profiler at "
                                 "import (profiler.py)"),
    "MXTPU_NO_NATIVE": ("0", True, "1 = disable the native io library"),
    # accepted-but-inert: the subsystem they tuned is XLA's problem now
    "MXNET_CPU_WORKER_NTHREADS": ("1", False, "engine threads (XLA)"),
    "MXNET_GPU_WORKER_NTHREADS": ("2", False, "engine threads (XLA)"),
    "MXNET_EXEC_BULK_EXEC_TRAIN": ("1", False, "op bulking (XLA fusion)"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": ("1", False,
                                       "op bulking (XLA fusion)"),
    "MXNET_EXEC_NUM_TEMP": ("1", False, "temp space pool (XLA alloc)"),
    "MXNET_GPU_MEM_POOL_RESERVE": ("5", False, "memory pool (XLA alloc)"),
    "MXNET_KVSTORE_REDUCTION_NTHREADS": ("4", False,
                                         "kvstore reduce (collectives)"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": ("1000000", False,
                                     "key sharding (collectives)"),
    "MXNET_KVSTORE_USETREE": ("0", False, "tree reduce (XLA scheduling)"),
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": ("1", False, "cudnn autotune (XLA)"),
    "MXNET_ENFORCE_DETERMINISM": ("0", False,
                                  "deterministic by construction"),
}


def env_bool(name, default=False):
    return os.environ.get(name, "1" if default else "0") in ("1", "true",
                                                             "True")


def naive_engine():
    """True when eager ops must run synchronously (the reference's
    NaiveEngine debug oracle)."""
    return os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def backward_do_mirror():
    """True when fused fwd+bwd programs should rematerialize the forward
    (reference MXNET_BACKWARD_DO_MIRROR)."""
    return env_bool("MXNET_BACKWARD_DO_MIRROR")


def summary():
    """Current values of every known MXNET_* variable and whether it has
    effect here (docs/faq/env_var.md analog)."""
    lines = ["%-36s %-10s %-6s %s" % ("Variable", "Value", "Wired", "Notes")]
    for name, (default, wired, doc) in sorted(_KNOWN.items()):
        lines.append("%-36s %-10s %-6s %s"
                     % (name, os.environ.get(name, default),
                        "yes" if wired else "inert", doc))
    return "\n".join(lines)
