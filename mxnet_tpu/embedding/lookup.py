"""Compiled embedding lookup: ONE gather program per step.

The hot path (docs/EMBEDDING.md):

* index batches arrive with any shape/values; the flattened indices pad
  to the next power of two and ride as a RUNTIME argument, so ragged
  batches re-use the cached program — zero steady-state retraces, the
  same discipline as the serving bucket ladder (mx.decode);
* the program is one ``jnp.take``; under the local row mesh
  (sharding.py) the gather carries a sharding constraint and GSPMD
  lowers it to gather -> all-to-all/psum over ICI. Padding slots use the
  sentinel index ``vocab`` with ``mode='fill', fill_value=0`` — NOT
  clip: a clipped sentinel would fetch (and on the grad path corrupt)
  the last real row, the PR 6 paged-KV lesson;
* cache key: (vocab, dim, dtype, padded length, mesh size). Index
  VALUES never key anything.

``lookup()`` is the single entry for the gluon block and the symbol op,
so eager and compiled callers share one program cache.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as _np

from .. import telemetry as _telemetry
from . import sharding as _sharding

__all__ = ["lookup", "lookup_partitioned", "pad_length", "LOOKUPS",
           "LOOKUP_RETRACES"]

# one increment per compiled-lookup dispatch; with
# embedding_sparse_dispatches this is the numerator of the bench's
# sparse_dispatches_per_step witness (docs/OBSERVABILITY.md)
LOOKUPS = _telemetry.REGISTRY.counter(
    "embedding_lookups",
    "compiled embedding lookup dispatches", vital=True)
# trace-time-only witness: flat in the steady state across ragged
# index batches (pinned by tests/test_embedding.py)
LOOKUP_RETRACES = _telemetry.REGISTRY.counter(
    "embedding_lookup_retraces",
    "embedding lookup program (re)traces", vital=True)

_SITE = _telemetry.RetraceSite(LOOKUP_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="embedding_lookup")

_LOCK = threading.Lock()
_PROGRAMS = {}           # cache key -> jitted fn   (guarded by _LOCK)


def pad_length(n):
    """Next power of two >= n (>= 1): the ladder that keeps ragged
    batches on cached programs."""
    cap = 1
    while cap < n:
        cap <<= 1
    return cap


def _build(mesh):
    @jax.jit
    def _lookup(w, idx):
        _SITE.note()
        if mesh is not None:
            w = jax.lax.with_sharding_constraint(
                w, _sharding.table_sharding(mesh))
        # sentinel=vocab padding drops to zeros via fill, never row V-1
        return jnp.take(w, idx, axis=0, mode="fill", fill_value=0)

    return _lookup


def lookup(weight_jax, idx_host, out_shape=None):
    """Gather rows ``idx_host`` (any-shape int array-like) from the
    (vocab, dim) table ``weight_jax``. Returns a jax array shaped
    ``idx.shape + (dim,)`` (or ``out_shape`` when given).

    One compiled dispatch when the flat length is already a power of
    two; otherwise the unpad slice adds a second (cheap, shape-keyed)
    device op — size batches pow-2 to stay at one (docs/EMBEDDING.md).
    """
    vocab, _dim = weight_jax.shape
    idx = _np.asarray(idx_host)  # analyze: ok(hostsync) indices arrive on host by contract (data pipeline output)
    flat = idx.reshape(-1).astype(_np.int32)
    n = flat.shape[0]
    cap = pad_length(max(n, 1))
    if cap != n:
        flat = _np.concatenate(
            [flat, _np.full(cap - n, vocab, _np.int32)])
    mesh = _sharding.local_mesh()
    if mesh is not None and (mesh.size <= 1 or vocab % mesh.size):
        mesh = None
    # mesh is part of the cache key (jax.sharding.Mesh hashes by
    # devices+axis names), so a changed mesh never reuses a stale program
    key = (int(vocab), int(_dim), str(weight_jax.dtype), cap, mesh)
    with _LOCK:
        fn = _PROGRAMS.get(key)
        if fn is None:
            fn = _PROGRAMS[key] = _build(mesh)
    from ..executor import _count_dispatch
    _count_dispatch()
    LOOKUPS.inc()
    out = _SITE.timed(fn, weight_jax, jnp.asarray(flat))
    if mesh is not None:
        # the (n, dim) result is small next to the table: land it on the
        # default device so eager consumers (the dense tower, autograd)
        # never mix an 8-device output with single-device arrays — the
        # GSPMD win is the table-side gather, not the result placement
        out = jax.device_put(out, jax.devices()[0])
    if cap != n:
        out = out[:n]
    shape = tuple(idx.shape) + (weight_jax.shape[1],) \
        if out_shape is None else tuple(out_shape)
    return out.reshape(shape)


def _build_partitioned(mesh):
    """ONE GSPMD program for the pod-partitioned gather: the (vocab,
    dim) table is row-sharded over the process 'dp' mesh, the global
    index vector is 'dp'-sharded (each rank's slice is its own padded
    batch), and XLA lowers the cross-shard gather to the on-fabric
    all-to-all — all-to-all(indices) -> local gather -> all-to-all(rows)
    in one launch (docs/EMBEDDING.md)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def _lookup(w, idx):
        _SITE.note()
        w = jax.lax.with_sharding_constraint(
            w, NamedSharding(mesh, P("dp", None)))
        out = jnp.take(w, idx, axis=0, mode="fill", fill_value=0)
        # each rank's addressable slice of the 'dp'-sharded result is
        # exactly its own batch's rows
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P("dp", None)))

    return _lookup


def lookup_partitioned(slab_jax, idx_host, lo, hi, vocab, out_shape=None):
    """Gather rows of a table row-partitioned ACROSS the process world:
    this rank owns rows ``[lo, hi)`` in ``slab_jax`` and every rank
    calls with its OWN batch (collective — all ranks must call once per
    step, SPMD order).

    GSPMD worlds (accelerator backends; also every single-process
    world, so tier-1 and ``MXNET_EMBED_PARTITION=1`` exercise this exact
    program): ONE jitted launch — the slab lifts metadata-only into the
    global row-sharded table and the gather's all-to-all happens inside
    the program. Host worlds (multi-process CPU backend): indices route
    to their owner ranks over ``dist.alltoall_bytes``, each owner runs
    the ONE compiled local gather on its slab, and rows route back —
    still one counted dispatch per rank per step.
    """
    from ..kvstore_tpu import dist
    idx = _np.asarray(idx_host)  # analyze: ok(hostsync) indices arrive on host by contract (data pipeline output)
    flat = idx.reshape(-1).astype(_np.int32)
    n = flat.shape[0]
    dim = slab_jax.shape[1]
    shape = tuple(idx.shape) + (dim,) if out_shape is None \
        else tuple(out_shape)
    world = dist.world_size()

    if dist.gspmd_supported():
        cap = pad_length(max(n, 1))
        if cap != n:
            flat = _np.concatenate(
                [flat, _np.full(cap - n, vocab, _np.int32)])
        mesh = _sharding.process_row_mesh()
        key = ("part", int(vocab), int(dim), str(slab_jax.dtype), cap,
               world, mesh)
        with _LOCK:
            fn = _PROGRAMS.get(key)
            if fn is None:
                fn = _PROGRAMS[key] = _build_partitioned(mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P
        w_g = jax.make_array_from_single_device_arrays(
            (vocab, dim), NamedSharding(mesh, P("dp", None)), [slab_jax])
        idx_g = jax.make_array_from_single_device_arrays(
            (world * cap,), NamedSharding(mesh, P("dp")),
            [jnp.asarray(flat)])
        from ..executor import _count_dispatch
        _count_dispatch()
        LOOKUPS.inc()
        if world > 1:
            # indices out + rows back, the fabric all-to-all payload
            _sharding.ALLTOALL_BYTES.inc(cap * 4 + cap * dim * 4)
        out = _SITE.timed(fn, w_g, idx_g)
        mine = out.addressable_data(0) if world > 1 else out
        if cap != n:
            mine = mine[:n]
        return mine.reshape(shape)

    # host transport: route each index to its owner rank, gather on the
    # owner's slab, route the rows back, undo the routing permutation
    per = hi - lo                    # equal slabs (partition eligibility)
    owner = _np.clip(flat // max(per, 1), 0, world - 1)
    order = _np.argsort(owner, kind="stable")
    counts = _np.bincount(owner, minlength=world)
    cuts = _np.cumsum(counts)[:-1]
    sends = _np.split(flat[order], cuts)
    payloads = [a.astype(_np.int32).tobytes() for a in sends]
    _sharding.ALLTOALL_BYTES.inc(sum(len(p) for p in payloads))
    got = dist.alltoall_bytes("emblookup", payloads)
    req = [_np.frombuffer(b, _np.int32) for b in got]
    sizes = [r.shape[0] for r in req]
    req_all = _np.concatenate(req) if req else _np.zeros(0, _np.int32)
    # slab-local ids; requests are owner-routed so they land in
    # [0, per) — anything else (corrupt id) hits the gather's fill
    rows = lookup(slab_jax, req_all - lo)
    rows_np = _np.asarray(rows, _np.float32)  # analyze: ok(hostsync) host transport return leg — the rows must cross the wire
    backs = _np.split(rows_np, _np.cumsum(sizes)[:-1])
    back_payloads = [b.tobytes() for b in backs]
    _sharding.ALLTOALL_BYTES.inc(sum(len(p) for p in back_payloads))
    mine = dist.alltoall_bytes("emblookup_rows", back_payloads)
    got_rows = _np.concatenate(
        [_np.frombuffer(b, _np.float32).reshape(-1, dim) for b in mine]) \
        if mine else _np.zeros((0, dim), _np.float32)
    out = _np.empty((n, dim), _np.float32)
    out[order] = got_rows
    return jnp.asarray(out).reshape(shape)
