"""Compiled embedding lookup: ONE gather program per step.

The hot path (docs/EMBEDDING.md):

* index batches arrive with any shape/values; the flattened indices pad
  to the next power of two and ride as a RUNTIME argument, so ragged
  batches re-use the cached program — zero steady-state retraces, the
  same discipline as the serving bucket ladder (mx.decode);
* the program is one ``jnp.take``; under the local row mesh
  (sharding.py) the gather carries a sharding constraint and GSPMD
  lowers it to gather -> all-to-all/psum over ICI. Padding slots use the
  sentinel index ``vocab`` with ``mode='fill', fill_value=0`` — NOT
  clip: a clipped sentinel would fetch (and on the grad path corrupt)
  the last real row, the PR 6 paged-KV lesson;
* cache key: (vocab, dim, dtype, padded length, mesh size). Index
  VALUES never key anything.

``lookup()`` is the single entry for the gluon block and the symbol op,
so eager and compiled callers share one program cache.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as _np

from .. import telemetry as _telemetry
from . import sharding as _sharding

__all__ = ["lookup", "pad_length", "LOOKUPS", "LOOKUP_RETRACES"]

# one increment per compiled-lookup dispatch; with
# embedding_sparse_dispatches this is the numerator of the bench's
# sparse_dispatches_per_step witness (docs/OBSERVABILITY.md)
LOOKUPS = _telemetry.REGISTRY.counter(
    "embedding_lookups",
    "compiled embedding lookup dispatches", vital=True)
# trace-time-only witness: flat in the steady state across ragged
# index batches (pinned by tests/test_embedding.py)
LOOKUP_RETRACES = _telemetry.REGISTRY.counter(
    "embedding_lookup_retraces",
    "embedding lookup program (re)traces", vital=True)

_SITE = _telemetry.RetraceSite(LOOKUP_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="embedding_lookup")

_LOCK = threading.Lock()
_PROGRAMS = {}           # cache key -> jitted fn   (guarded by _LOCK)


def pad_length(n):
    """Next power of two >= n (>= 1): the ladder that keeps ragged
    batches on cached programs."""
    cap = 1
    while cap < n:
        cap <<= 1
    return cap


def _build(mesh):
    @jax.jit
    def _lookup(w, idx):
        _SITE.note()
        if mesh is not None:
            w = jax.lax.with_sharding_constraint(
                w, _sharding.table_sharding(mesh))
        # sentinel=vocab padding drops to zeros via fill, never row V-1
        return jnp.take(w, idx, axis=0, mode="fill", fill_value=0)

    return _lookup


def lookup(weight_jax, idx_host, out_shape=None):
    """Gather rows ``idx_host`` (any-shape int array-like) from the
    (vocab, dim) table ``weight_jax``. Returns a jax array shaped
    ``idx.shape + (dim,)`` (or ``out_shape`` when given).

    One compiled dispatch when the flat length is already a power of
    two; otherwise the unpad slice adds a second (cheap, shape-keyed)
    device op — size batches pow-2 to stay at one (docs/EMBEDDING.md).
    """
    vocab, _dim = weight_jax.shape
    idx = _np.asarray(idx_host)  # analyze: ok(hostsync) indices arrive on host by contract (data pipeline output)
    flat = idx.reshape(-1).astype(_np.int32)
    n = flat.shape[0]
    cap = pad_length(max(n, 1))
    if cap != n:
        flat = _np.concatenate(
            [flat, _np.full(cap - n, vocab, _np.int32)])
    mesh = _sharding.local_mesh()
    if mesh is not None and (mesh.size <= 1 or vocab % mesh.size):
        mesh = None
    # mesh is part of the cache key (jax.sharding.Mesh hashes by
    # devices+axis names), so a changed mesh never reuses a stale program
    key = (int(vocab), int(_dim), str(weight_jax.dtype), cap, mesh)
    with _LOCK:
        fn = _PROGRAMS.get(key)
        if fn is None:
            fn = _PROGRAMS[key] = _build(mesh)
    from ..executor import _count_dispatch
    _count_dispatch()
    LOOKUPS.inc()
    out = _SITE.timed(fn, weight_jax, jnp.asarray(flat))
    if mesh is not None:
        # the (n, dim) result is small next to the table: land it on the
        # default device so eager consumers (the dense tower, autograd)
        # never mix an 8-device output with single-device arrays — the
        # GSPMD win is the table-side gather, not the result placement
        out = jax.device_put(out, jax.devices()[0])
    if cap != n:
        out = out[:n]
    shape = tuple(idx.shape) + (weight_jax.shape[1],) \
        if out_shape is None else tuple(out_shape)
    return out.reshape(shape)
