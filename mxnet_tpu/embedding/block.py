"""ShardedEmbedding: the gluon front end of the embedding subsystem.

Differences from ``gluon.nn.Embedding`` (docs/EMBEDDING.md):

* the table is looked up by the COMPILED lookup engine (lookup.py) —
  one gather dispatch per forward, sharded over the local row mesh —
  instead of riding the eager op tape;
* the table is NOT differentiated through: each recorded forward marks
  its output as an autograd leaf, so ``backward()`` deposits the dense
  output gradient there and ``sparse_grad()`` reassembles it as a
  row_sparse gradient (indices straight from the forward batch,
  duplicates welcome — the kvstore engine coalesces in-program);
* updates flow through ``kv.push`` (the compiled SparseApplyEngine when
  the optimizer implements ``_fused_sparse_sig``), not the dense
  Trainer. The weight Parameter is created with ``grad_req='null'`` so
  a Trainer over ``collect_params()`` skips it; call ``sparse_push()``
  after ``backward()`` instead.

``attach_to_kvstore`` ALIASES the parameter storage to the kvstore's
stored value: the engine updates the table in place (donated buffers),
so the next forward reads fresh rows with zero pulls — the
row_sparse_pull round trip is for explicit sharded-serving reads, not
the training loop.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from ..gluon.block import Block
from . import sharding as _sharding
from . import lookup as _lookup

__all__ = ["ShardedEmbedding"]


class ShardedEmbedding(Block):
    """Row-sharded embedding table with a compiled sparse grad path."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = int(input_dim)
        self._output_dim = int(output_dim)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(self._input_dim, self._output_dim),
                dtype=dtype, init=weight_initializer,
                grad_req="null", grad_stype="row_sparse")
        self._tape = []        # (flat int32 indices, marked output)
        self._kv = None
        self._kv_key = None
        self._placed = False
        self._partition = None  # (lo, hi) once pod-partitioned

    # -- forward --------------------------------------------------------
    def forward(self, x):
        from .. import autograd
        w = self.weight.data()
        if not self._placed:
            w._set_data(_sharding.place_table(w._data))
            self._placed = True
        idx = _np.asarray(x._data if isinstance(x, NDArray) else x)
        with autograd.pause():
            if self._partition is not None:
                lo, hi = self._partition
                out = NDArray(
                    _lookup.lookup_partitioned(w._data, idx, lo, hi,
                                               self._input_dim),
                    w.context)
            else:
                out = NDArray(_lookup.lookup(w._data, idx), w.context)
        if autograd.is_recording():
            # leaf-mark the lookup output: backward stops here and the
            # dense dy lands in out._grad, batch-sized — the huge table
            # never joins the tape
            out.attach_grad()
            self._tape.append(
                (idx.reshape(-1).astype(_np.int32), out))
        return out

    # -- sparse grad assembly -------------------------------------------
    def sparse_grad(self):
        """The row_sparse gradient of every recorded forward since the
        last call (indices may repeat across and within batches — the
        push path coalesces). None when nothing was recorded or no
        backward has run."""
        from ..ndarray.sparse import RowSparseNDArray
        datas, idxs = [], []
        for idx_flat, out in self._tape:
            if out._grad is None:
                continue
            datas.append(out._grad._data.reshape(-1, self._output_dim))
            idxs.append(idx_flat)
        self._tape.clear()
        if not datas:
            return None
        data = jnp.concatenate(datas) if len(datas) > 1 else datas[0]
        idx = _np.concatenate(idxs) if len(idxs) > 1 else idxs[0]
        w = self.weight.data()
        return RowSparseNDArray(data, jnp.asarray(idx),
                                (self._input_dim, self._output_dim),
                                w.context)

    # -- kvstore glue ----------------------------------------------------
    def attach_to_kvstore(self, kv, key=None):
        """Register the table with ``kv`` and alias the parameter to the
        stored value so in-place engine updates are immediately visible
        to the next forward.

        In a multi-process world (or under ``MXNET_EMBED_PARTITION=1``)
        an eligible table is row-partitioned ACROSS hosts
        (docs/EMBEDDING.md "Multi-host partitioning"): the store keeps
        only this rank's ``sharding.row_range`` slab, table bytes per
        host scale as 1/W, and lookups/pushes route through the
        all-to-all transport. Ineligible tables (vocab not divisible by
        the world, non-f32) stay replicated under a narrow
        ``kvstore_fallbacks`` slug."""
        if self.weight._data is None:
            raise MXNetError(
                "initialize() the block before attach_to_kvstore")
        key = key if key is not None else "embedding:%s" % self.weight.name
        kv.init(key, self.weight.data())
        stored = kv._store[key]
        dec, arg = _sharding.partition_decision(self._input_dim,
                                                stored.dtype)
        if dec == "partition":
            from ..kvstore_tpu import dist
            lo, hi = _sharding.row_range(self._input_dim, dist.rank(),
                                         arg)
            slab = NDArray(stored._data[lo:hi], stored.context)
            kv._store[key] = slab
            kv._partitioned[key] = (lo, hi, self._input_dim)
            self._partition = (lo, hi)
            stored = slab
        else:
            if arg is not None:
                from ..kvstore import _note_fallback
                _note_fallback(
                    arg, detail="embedding table stays replicated")
            stored._set_data(_sharding.place_table(stored._data))
        self.weight._data = stored
        self._placed = True
        self._kv, self._kv_key = kv, key
        _sharding.account_bytes(key, stored._data.nbytes)
        _sharding.account_table_bytes(key, stored._data.nbytes)
        return key

    def sparse_push(self, kv=None, key=None, priority=0):
        """Push the recorded sparse gradient (compiled engine when the
        optimizer is eligible; eager lazy update otherwise)."""
        kv = kv if kv is not None else self._kv
        key = key if key is not None else self._kv_key
        if kv is None or key is None:
            raise MXNetError(
                "sparse_push needs attach_to_kvstore (or explicit "
                "kv/key)")
        grad = self.sparse_grad()
        if grad is None:
            return
        kv.push(key, grad, priority=priority)

    def __repr__(self):
        return "ShardedEmbedding(%d -> %d)" % (self._input_dim,
                                               self._output_dim)
