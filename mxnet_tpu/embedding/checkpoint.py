"""Sharded-table checkpointing: each rank persists its OWN row range.

Rides the PR 7 multi-host protocol (checkpoint/manifest.py +
checkpoint/multihost.py choreography), specialized for (vocab, dim)
embedding tables whose full weight never fits one host at
recommendation scale:

1. every rank writes ``<prefix>-<tag>.embshard<r>`` — a crash-safe
   (tmp + fsync + rename) pickle holding, for each table, THIS rank's
   owned row range (sharding.row_range) of the weight, optimizer state,
   and error-feedback residual, with explicit (lo, hi) bounds;
2. ``dist.barrier`` — nobody publishes until every shard is durable;
3. rank 0 publishes ``<prefix>-<tag>.emb.json`` listing every shard
   file with its CRC — the single commit point.

Because each shard records its absolute row bounds, ``load_tables``
reassembles full tables under ANY world size — a W=8 checkpoint
restores into a W=2 (or single-host) job, and any-host-can-die resume
follows from the all-durable barrier. ``latest_tables`` walks tags
newest-first and skips over checkpoints whose manifest or shard CRCs
fail, the same corrupt-tag fallback the dense protocol gives
(docs/CHECKPOINT.md). Dense parameters stay in the legacy single-file
formats; only embedding tables go through this path.
"""
from __future__ import annotations

import glob
import json
import os
import pickle

import numpy as _np

from ..base import MXNetError
from ..checkpoint import manifest as _manifest
from . import sharding as _sharding

__all__ = ["save_tables", "load_tables", "latest_tables", "list_table_tags"]

_SHARD_FMT = "%s-%s.embshard%d"
_MANIFEST_FMT = "%s-%s.emb.json"


def _world():
    from ..kvstore_tpu import dist
    return dist.rank(), dist.world_size()


def _as_np(arr):
    if arr is None:
        return None
    if isinstance(arr, (tuple, list)):
        return [_as_np(a) for a in arr]
    return _np.asarray(arr._data if hasattr(arr, "_data") else arr)


def save_tables(prefix, tag, tables, states=None, residuals=None,
                partitioned=None):
    """Checkpoint ``tables`` ({name: NDArray-or-jax (vocab, dim)}), with
    optional parallel dicts of optimizer states and error-feedback
    residuals. Collective in a multi-process world: every rank must
    call with the same names and tag. Returns the manifest path (every
    rank; only rank 0 wrote it).

    ``partitioned`` ({name: (lo, hi, vocab)}, e.g. ``kv._partitioned``)
    marks entries whose value is THIS RANK'S OWNED ROW SLAB of a
    pod-partitioned table (docs/EMBEDDING.md): the slab persists as
    rows [lo, hi) of the full (vocab, dim) table, and the matching
    state/residual entries are slab-shaped and persist whole instead of
    being sliced. The shard format is identical either way — because
    bounds are absolute, a W=2 partitioned checkpoint restores into a
    W=1 (or replicated) job through the same ``load_tables``."""
    rank, world = _world()
    states = states or {}
    residuals = residuals or {}
    partitioned = partitioned or {}
    shard = {}
    for name, table in tables.items():
        host = _as_np(table)
        st = _as_np(states.get(name))
        res = _as_np(residuals.get(name))
        part = partitioned.get(name)
        if part is not None:
            lo, hi, vocab = int(part[0]), int(part[1]), int(part[2])
            rows = host                       # already the owned slab
            full_shape = (vocab,) + tuple(host.shape[1:])
            st_rows = [_np.ascontiguousarray(s) for s in st] \
                if isinstance(st, list) \
                else (_np.ascontiguousarray(st) if st is not None
                      else None)
            res_rows = _np.ascontiguousarray(res) \
                if res is not None else None
        else:
            rows, lo, hi = _sharding.owned_slice(host, rank, world)
            full_shape = tuple(host.shape)
            st_rows = [_np.ascontiguousarray(s[lo:hi]) for s in st] \
                if isinstance(st, list) \
                else (_np.ascontiguousarray(st[lo:hi])
                      if st is not None else None)
            res_rows = _np.ascontiguousarray(res[lo:hi]) \
                if res is not None else None
        shard[name] = {
            "lo": lo, "hi": hi,
            "shape": full_shape, "dtype": str(host.dtype),
            "rows": _np.ascontiguousarray(rows),
            "state": st_rows,
            "residual": res_rows,
        }
    shard_path = _SHARD_FMT % (prefix, tag, rank)
    _manifest.atomic_write(shard_path, pickle.dumps(shard, protocol=4))

    from ..kvstore_tpu import dist
    if world > 1:
        # all-durable barrier: the manifest below is the commit point,
        # so it must not publish shards that are still in flight
        dist.barrier("embckpt-shards")
    manifest_path = _MANIFEST_FMT % (prefix, tag)
    if rank == 0:
        files = {}
        for r in range(world):
            p = _SHARD_FMT % (prefix, tag, r)
            files[os.path.basename(p)] = {
                "crc32": _manifest.crc32_file(p),
                "bytes": os.path.getsize(p),
            }
        doc = {
            "format": "mxnet_tpu-embedding-shards-v1",
            "tag": str(tag),
            "world": world,
            "tables": {n: {"shape": list(s["shape"]),
                           "dtype": s["dtype"]}
                       for n, s in shard.items()},
            "files": files,
        }
        _manifest.atomic_write(
            manifest_path,
            json.dumps(doc, indent=2, sort_keys=True).encode())
    if world > 1:
        dist.barrier("embckpt-commit")
    return manifest_path


def _validate(prefix, manifest_path):
    try:
        with open(manifest_path, "rb") as f:
            doc = json.loads(f.read().decode())
    except (OSError, ValueError):
        return None
    if doc.get("format") != "mxnet_tpu-embedding-shards-v1":
        return None
    base = os.path.dirname(os.path.abspath(manifest_path))
    for fname, meta in doc.get("files", {}).items():
        path = os.path.join(base, fname)
        try:
            # crc32_file returns a (size, crc) tuple; JSON round-trips
            # it as a list — normalize both sides before comparing
            got = _manifest.crc32_file(path)
            want = meta["crc32"]
            got = list(got) if isinstance(got, (tuple, list)) else [got]
            want = list(want) if isinstance(want, (tuple, list)) \
                else [want]
            if got != want:
                return None
        except OSError:
            return None
    return doc


def list_table_tags(prefix):
    """Tags with a published embedding manifest, oldest first (mtime
    order, matching checkpoint/manifest.list_tags)."""
    paths = glob.glob(_MANIFEST_FMT % (prefix, "*"))
    paths.sort(key=lambda p: (os.path.getmtime(p), p))
    tags = []
    for p in paths:
        suffix = p[len(prefix) + 1:]
        tags.append(suffix[:-len(".emb.json")])
    return tags


def latest_tables(prefix):
    """The newest tag whose manifest AND every shard validate, or None
    — a torn/corrupt newest checkpoint falls back to the previous one
    instead of failing resume."""
    for tag in reversed(list_table_tags(prefix)):
        if _validate(prefix, _MANIFEST_FMT % (prefix, tag)) is not None:
            return tag
    return None


def load_tables(prefix, tag=None):
    """Reassemble full tables from every shard of ``tag`` (default: the
    newest valid tag). Returns {name: {"weight": np, "state":
    np|list|None, "residual": np|None}}. World-size independent: row
    bounds come from the shards, not from the current world."""
    if tag is None:
        tag = latest_tables(prefix)
        if tag is None:
            raise MXNetError(
                "no valid embedding checkpoint under prefix %r" % prefix)
    doc = _validate(prefix, _MANIFEST_FMT % (prefix, tag))
    if doc is None:
        raise MXNetError(
            "embedding checkpoint %r tag %r is missing or corrupt"
            % (prefix, tag))
    out = {}
    for name, meta in doc["tables"].items():
        shape = tuple(meta["shape"])
        out[name] = {
            "weight": _np.zeros(shape, meta["dtype"]),
            "state": None,
            "residual": None,
        }
    for r in range(int(doc["world"])):
        with open(_SHARD_FMT % (prefix, tag, r), "rb") as f:
            shard = pickle.load(f)
        for name, rec in shard.items():
            dst = out[name]
            lo, hi = rec["lo"], rec["hi"]
            dst["weight"][lo:hi] = rec["rows"]
            st = rec.get("state")
            if st is not None:
                if isinstance(st, list):
                    if dst["state"] is None:
                        dst["state"] = [
                            _np.zeros((out[name]["weight"].shape[0],)
                                      + s.shape[1:], s.dtype)
                            for s in st]
                    for d, s in zip(dst["state"], st):
                        d[lo:hi] = s
                else:
                    if dst["state"] is None:
                        dst["state"] = _np.zeros(
                            (out[name]["weight"].shape[0],)
                            + st.shape[1:], st.dtype)
                    dst["state"][lo:hi] = st
            res = rec.get("residual")
            if res is not None:
                if dst["residual"] is None:
                    dst["residual"] = _np.zeros(
                        (out[name]["weight"].shape[0],) + res.shape[1:],
                        res.dtype)
                dst["residual"][lo:hi] = res
    return out
