"""SparseApplyEngine: the compiled row_sparse gradient pipeline.

One push of row_sparse gradients for one table runs as ONE jitted
program (docs/EMBEDDING.md):

    dedup/coalesce -> 2-bit-compress unique rows (error feedback)
        -> [cross-host reduce] -> lazy sparse-apply

extending the dense bucket engines (kvstore_fused.py, PR 2;
kvstore_tpu/engine.py, PR 7) to the row_sparse storage type the
reference kvstore treats as its native gradient format. Design points:

* **Runtime-vs-static split.** Index VALUES and row payloads are
  runtime arguments; only (table shape, per-stream padded capacities,
  optimizer signature, compression threshold) key the program cache.
  Capacities pad to the next power of two, so ragged non-zero counts
  re-use cached programs — zero steady-state retraces (the
  ``embedding_sparse_retraces`` witness).
* **In-program coalesce.** Duplicate indices merge by a stable
  sort + segment-sum whose per-group addition order equals the eager
  ``_coalesce_rsp`` (host ``np.unique``) order, so the eager path stays
  a bit-for-bit parity oracle. Padding uses the sentinel index
  ``vocab`` with gather ``mode='fill'(0)`` / scatter ``mode='drop'`` —
  never clip (the PR 6 paged-KV out-of-bounds lesson).
* **Lazy updates.** The apply touches ONLY the gradient's rows, with
  the exact op sequence of the eager lazy updates in
  ndarray/sparse.py (``sparse_sgd_update`` / ``sparse_adagrad_update``
  / ``sparse_group_adagrad_update``), selected by
  ``Optimizer._fused_sparse_sig()``.
* **Residual ownership.** Per-table error-feedback residuals are
  donated (vocab, dim) arrays owned by the engine exactly like the
  dense engine's flat buffers: seeded from
  ``kv._compression_residuals[(key, "rsp")]``, spilled back there by
  ``spill_residuals()`` (checkpoint capture and routing changes call
  ``kv._sync_engine()`` first, same contract as the dense engine).
* **Cross-host.** In a multi-process world (``kvstore='tpu'``) the
  engine mirrors the PR 7 host transport: a local program coalesces +
  quantizes, the (indices, rows) payload rides one
  ``dist.allgather_bytes``, and a second program coalesces the union
  in deterministic rank order and applies. Compression runs BEFORE the
  wire — that is what it is for. A single GSPMD program spanning the
  process mesh (like the dense engine's accelerator path) is future
  work; the host transport keeps every rank's replicated table
  bit-identical, which is the invariant checkpointing relies on.
"""
from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray
from .. import telemetry as _telemetry
from ..kvstore_fused import two_bit_quantize
from . import sharding as _sharding
from .lookup import pad_length

__all__ = ["SparseApplyEngine", "SPARSE_DISPATCHES", "SPARSE_RETRACES"]

# compiled sparse-apply program launches (1 per push single-process,
# 2 on the multi-process host transport); with embedding_lookups this
# is the bench's sparse_dispatches_per_step witness
SPARSE_DISPATCHES = _telemetry.REGISTRY.counter(
    "embedding_sparse_dispatches",
    "compiled sparse-apply program dispatches", vital=True)
# trace-time-only: flat in the steady state across ragged nnz counts
SPARSE_RETRACES = _telemetry.REGISTRY.counter(
    "embedding_sparse_retraces",
    "compiled sparse-apply program (re)traces", vital=True)

_SITE = _telemetry.RetraceSite(SPARSE_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="embedding_sparse")

_RSP_RES = "rsp"      # device slot in kv._compression_residuals keys


def _coalesce(idx, rows, vocab):
    """In-program dedup: stable-sorted unique indices compacted to the
    low slots (sentinel ``vocab`` elsewhere) + per-index row sums.
    Stable sort keeps duplicate groups in original order, so the
    segment sums add in the same order as the eager host coalesce."""
    order = jnp.argsort(idx)                       # jax argsort: stable
    si = idx[order]
    sr = rows[order]
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), si[1:] != si[:-1]])
    seg = jnp.cumsum(head) - 1
    uidx = jnp.full(si.shape, vocab, si.dtype).at[seg].set(si)
    urows = jax.ops.segment_sum(sr, seg, num_segments=si.shape[0])
    return uidx, urows


def _sparse_apply(sig, w, state, uidx, g, lr, wd, rescale):
    """The lazy optimizer apply on coalesced (uidx, g): same op
    sequence as the eager updates in ndarray/sparse.py restricted to
    the touched rows. Sentinel slots compute garbage-free zeros and
    drop at the scatter."""
    kind, hyper, clip = sig
    g = g * rescale
    if clip is not None and clip >= 0:
        g = jnp.clip(g, -clip, clip)
    wr = jnp.take(w, uidx, axis=0, mode="fill", fill_value=0)
    if kind == "sgd":
        g = g + wd * wr
        if state is not None:            # hyper == momentum != 0
            mr = hyper * jnp.take(state, uidx, axis=0, mode="fill",
                                  fill_value=0) - lr * g
            state = state.at[uidx].set(mr, mode="drop")
            new_wr = wr + mr
        else:
            new_wr = wr - lr * g
    elif kind == "adagrad":              # hyper == epsilon
        hr = jnp.take(state, uidx, axis=0, mode="fill",
                      fill_value=0) + jnp.square(g)
        state = state.at[uidx].set(hr, mode="drop")
        new_wr = wr - lr * (g / jnp.sqrt(hr + hyper) + wd * wr)
    elif kind == "group_adagrad":        # hyper == epsilon, no wd
        hr = jnp.take(state, uidx, axis=0, mode="fill", fill_value=0) \
            + jnp.mean(jnp.square(g), axis=1, keepdims=True)
        state = state.at[uidx].set(hr, mode="drop")
        new_wr = wr - lr * g / jnp.sqrt(hr + hyper)
    else:
        raise MXNetError("unknown sparse-apply signature %r" % (kind,))
    w = w.at[uidx].set(new_wr, mode="drop")
    return w, state


class SparseApplyEngine:
    """Per-kvstore compiled row_sparse push engine (one instance per
    store, one program per table signature). ``cross_host=True`` (the
    ``kvstore='tpu'`` store) routes through the host transport when the
    dist world has more than one process."""

    def __init__(self, kv, cross_host=False):
        self._kv = kv
        self._cross_host = cross_host
        self._programs = {}
        self._residuals = {}           # key -> donated (vocab, dim) array
        self._lock = threading.Lock()

    # -- eligibility ----------------------------------------------------
    def ineligible_reason(self, key, vlist):
        """None when this push may take the compiled sparse path, else a
        BOUNDED reason slug (a ``kvstore_fallbacks`` label — keep key
        names and shapes out). Narrower than the dense engine's single
        ``sparse_value``: unsupported OPTIMIZER and ineligible DTYPE
        fall back for different reasons and warn separately."""
        from ..ndarray.sparse import RowSparseNDArray
        from ..optimizer import Updater
        if not all(isinstance(v, RowSparseNDArray) for v in vlist):
            return "sparse_mixed_stype"
        updater = self._kv._updater
        if updater is None:
            return "sparse_assign_push"
        if not isinstance(updater, Updater):
            return "sparse_custom_updater"
        opt = updater.optimizer
        sig = getattr(opt, "_fused_sparse_sig", lambda: None)()
        if sig is None:
            return ("sparse_unsupported_optimizer:%s"
                    % type(opt).__name__)
        stored = self._kv._store.get(key)
        if stored is None:
            return "sparse_key_not_initialized"
        if stored.dtype != _np.float32 \
                or any(v.dtype != _np.float32 for v in vlist):
            return "sparse_ineligible_dtype"
        part = self._kv._partitioned.get(key)
        expect = (part[2], stored.shape[1]) if part is not None \
            else tuple(stored.shape)   # gradients carry the FULL vocab
        if len(stored.shape) != 2 \
                or any(tuple(v.shape) != expect for v in vlist):
            return "sparse_shape_mismatch"
        return None

    # -- residual ownership (mirrors FusedBucketEngine flat buffers) ----
    def _residual(self, key, vocab, dim):
        res = self._residuals.get(key)
        if res is None:
            seed = self._kv._compression_residuals.get((key, _RSP_RES))
            if seed is not None and tuple(seed.shape) == (vocab, dim):
                res = jnp.array(seed._data)      # copy: we will donate
            else:
                res = jnp.zeros((vocab, dim), jnp.float32)
            self._residuals[key] = res
        return res

    def spill_residuals(self):
        """Hand residual ownership back to the per-key dict (checkpoint
        capture, routing changes — kv._sync_engine's contract)."""
        with self._lock:
            for key, arr in self._residuals.items():
                self._kv._compression_residuals[(key, _RSP_RES)] = \
                    NDArray(arr)
            self._residuals.clear()

    # -- dispatch -------------------------------------------------------
    def push(self, key, vlist, priority=0):
        """Dispatch one table's row_sparse push through the compiled
        pipeline (the caller has already checked eligibility)."""
        del priority                       # per-table: nothing to order
        from ..kvstore import _updater_key
        kv = self._kv
        updater = kv._updater
        opt = updater.optimizer
        uk = _updater_key(key)
        stored = kv._store[key]
        vocab, dim = stored.shape
        part = kv._partitioned.get(key)
        if part is not None:
            # stored is this rank's row slab; sentinels and coalesce
            # bounds use the GLOBAL vocab from the partition registry
            vocab = part[2]
        if uk not in updater.states:
            updater.states[uk] = opt.create_state_multi_precision(
                uk, stored)
            updater.states_synced[uk] = True
        state_nd = updater.states[uk]
        opt._update_count(uk)
        lr = _np.float32(opt._get_lr(uk))
        wd = _np.float32(opt._get_wd(uk))
        rescale = _np.float32(opt.rescale_grad)
        sig = opt._fused_sparse_sig()
        comp = kv._compression
        threshold = float(comp.threshold) if comp is not None else None

        if part is not None:
            with self._lock:
                new = self._dispatch_partition(
                    key, sig, stored, state_nd, threshold, part, dim,
                    vlist, lr, wd, rescale)
            new_w, new_state = new
            stored._set_data(new_w)
            if state_nd is not None:
                state_nd._set_data(new_state)
            nbytes = stored._data.nbytes \
                + (state_nd._data.nbytes if state_nd is not None else 0) \
                + (self._residuals[key].nbytes
                   if key in self._residuals else 0)
            _sharding.account_bytes(key, nbytes)
            _sharding.account_table_bytes(key, stored._data.nbytes)
            return

        idxs, rowss, caps = [], [], []
        for v in vlist:
            n = int(v._sp_indices.shape[0])
            cap = pad_length(max(n, 1))
            idx = v._sp_indices.astype(jnp.int32)
            rows = v._sp_data.astype(jnp.float32)
            if cap != n:
                idx = jnp.concatenate(
                    [idx, jnp.full((cap - n,), vocab, jnp.int32)])
                rows = jnp.concatenate(
                    [rows, jnp.zeros((cap - n, dim), jnp.float32)])
            idxs.append(idx)
            rowss.append(rows)
            caps.append(cap)

        if len(stored._data.sharding.device_set) > 1:
            # the table is row-sharded over the local mesh while the
            # gradient streams arrive committed to the default device
            # (lookup lands its output there); replicate the small
            # streams onto the table's mesh or jit rejects the mix of
            # device sets
            mesh = _sharding.local_mesh()
            if mesh is not None:
                rep = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec())
                idxs = [jax.device_put(i, rep) for i in idxs]
                rowss = [jax.device_put(r, rep) for r in rowss]

        from ..kvstore_tpu import dist
        world = dist.world_size() if self._cross_host else 1
        with self._lock:
            if world > 1:
                new = self._dispatch_host(key, sig, stored, state_nd,
                                          threshold, vocab, dim,
                                          tuple(caps), idxs, rowss,
                                          lr, wd, rescale)
            else:
                new = self._dispatch_local(key, sig, stored, state_nd,
                                           threshold, vocab, dim,
                                           tuple(caps), idxs, rowss,
                                           lr, wd, rescale)
        new_w, new_state = new
        stored._set_data(new_w)
        if state_nd is not None:
            state_nd._set_data(new_state)
        nbytes = stored._data.nbytes \
            + (state_nd._data.nbytes if state_nd is not None else 0) \
            + (self._residuals[key].nbytes
               if key in self._residuals else 0)
        _sharding.account_bytes(key, nbytes)

    def _program(self, cache_key, builder):
        fn = self._programs.get(cache_key)
        if fn is None:
            fn = self._programs[cache_key] = builder()
        return fn

    def _dispatch_local(self, key, sig, stored, state_nd, threshold,
                        vocab, dim, caps, idxs, rowss, lr, wd, rescale):
        """Single-process: the whole pipeline is ONE donated program."""
        has_state = state_nd is not None
        fn = self._program(
            ("local", sig, caps, vocab, dim, threshold, has_state),
            lambda: _build_local(sig, vocab, threshold, has_state))
        res_in = self._residual(key, vocab, dim) \
            if threshold is not None else ()
        from ..executor import _count_dispatch
        _count_dispatch()
        SPARSE_DISPATCHES.inc()
        out = _SITE.timed(
            fn, stored._data, state_nd._data if has_state else (),
            res_in, tuple(idxs), tuple(rowss), lr, wd,
            jnp.float32(rescale))
        new_w, new_state, new_res = out
        if threshold is not None:
            self._residuals[key] = new_res
        return new_w, (new_state if has_state else None)

    def _dispatch_partition(self, key, sig, stored, state_nd, threshold,
                            part, dim, vlist, lr, wd, rescale):
        """Pod-partitioned apply: ``stored`` is this rank's row slab of
        the full (vocab, dim) table and the incoming gradients carry
        GLOBAL indices. ONE cross-host sparse launch per push
        (docs/EMBEDDING.md) instead of the replicated host transport's
        two.

        GSPMD worlds (and every single-process world — tier-1 coverage
        via ``MXNET_EMBED_PARTITION=1``): one jitted program over the
        process 'dp' mesh coalesces the global union and applies to the
        row-sharded table; XLA lowers the index/row exchange to the
        fabric all-to-all. Host worlds (multi-process CPU backend): raw
        (index, row) pairs route to their owner ranks over
        ``dist.alltoall_bytes`` and each owner runs the ONE local
        coalesce->quantize->apply program on its slab.

        Note the error-feedback difference from the replicated host
        transport: compression quantizes ONCE on the owner-side
        coalesced union against the slab residual (exact error
        feedback), not per-rank before the wire — the wire carries raw
        gradients routed by ownership, already 1/W of the replicated
        all-to-all-gather traffic."""
        from ..kvstore_tpu import dist
        lo, hi, vocab = part
        slab_rows = hi - lo
        world = dist.world_size()
        if dist.gspmd_supported():
            return self._dispatch_partition_gspmd(
                key, sig, stored, state_nd, threshold, part, dim, vlist,
                lr, wd, rescale, world)
        idx_np = _np.concatenate(
            [_np.asarray(v._sp_indices) for v in vlist]).astype(_np.int32)  # analyze: ok(hostsync) host transport: owner routing reads the indices on host by design
        rows_np = _np.concatenate(
            [_np.asarray(v._sp_data).reshape(-1, dim)  # analyze: ok(hostsync) host transport payload — rows must cross the wire anyway
             for v in vlist]).astype(_np.float32)
        owner = _np.clip(idx_np // max(slab_rows, 1), 0, world - 1)
        order = _np.argsort(owner, kind="stable")
        counts = _np.bincount(owner, minlength=world)
        cuts = _np.cumsum(counts)[:-1]
        si, sr = idx_np[order], rows_np[order]
        payloads = [i.tobytes() + r.tobytes()
                    for i, r in zip(_np.split(si, cuts),
                                    _np.split(sr, cuts))]
        _sharding.ALLTOALL_BYTES.inc(sum(len(p) for p in payloads))
        got = dist.alltoall_bytes("embgrad", payloads)
        all_i, all_r = [], []
        for buf in got:
            nn = len(buf) // (4 + 4 * dim)
            all_i.append(_np.frombuffer(buf[:4 * nn], _np.int32))
            all_r.append(_np.frombuffer(buf[4 * nn:], _np.float32)
                         .reshape(nn, dim))
        idx_g = _np.concatenate(all_i) - lo          # slab-local ids
        rows_g = _np.concatenate(all_r)
        nn = idx_g.shape[0]
        cap = pad_length(max(nn, 1))
        if cap != nn:
            idx_g = _np.concatenate(
                [idx_g, _np.full(cap - nn, slab_rows, _np.int32)])
            rows_g = _np.concatenate(
                [rows_g, _np.zeros((cap - nn, dim), _np.float32)])
        # the owned union runs the SAME single-launch local program as a
        # single-process table, on the slab (sentinel = slab_rows)
        return self._dispatch_local(
            key, sig, stored, state_nd, threshold, slab_rows, dim,
            (cap,), [jnp.asarray(idx_g)], [jnp.asarray(rows_g)], lr, wd,
            rescale)

    def _dispatch_partition_gspmd(self, key, sig, stored, state_nd,
                                  threshold, part, dim, vlist, lr, wd,
                                  rescale, world):
        """ONE GSPMD launch: every rank's padded (global-index, row)
        stream lifts into 'dp'-sharded global arrays, the slab/state/
        residual lift into row-sharded (vocab, dim) tables, and the
        program coalesces the global union, quantizes against the
        row-sharded residual, and lazily applies — XLA inserts the
        all-to-alls."""
        from ..kvstore_tpu import dist
        from ..executor import _count_dispatch
        from jax.sharding import NamedSharding, PartitionSpec as P
        lo, hi, vocab = part
        has_state = state_nd is not None
        mesh = _sharding.process_row_mesh()
        idx = jnp.concatenate([v._sp_indices.astype(jnp.int32)
                               for v in vlist]) if len(vlist) > 1 \
            else vlist[0]._sp_indices.astype(jnp.int32)
        rows = jnp.concatenate([v._sp_data.astype(jnp.float32)
                                for v in vlist]) if len(vlist) > 1 \
            else vlist[0]._sp_data.astype(jnp.float32)
        n = int(idx.shape[0])
        cap = pad_length(max(n, 1))
        if world > 1:
            # ragged nnz: agree on the pow2 pad rung so every rank lifts
            # the same global shape (one tiny host exchange; the ladder
            # keeps it steady-state stable and the LAUNCH count at one)
            caps = dist.allgather_bytes(
                "embcap", _np.int32(cap).tobytes())
            cap = max(int(_np.frombuffer(b, _np.int32)[0]) for b in caps)
        if cap != n:
            idx = jnp.concatenate(
                [idx, jnp.full((cap - n,), vocab, jnp.int32)])
            rows = jnp.concatenate(
                [rows, jnp.zeros((cap - n, dim), jnp.float32)])
        res_in = self._residual(key, hi - lo, dim) \
            if threshold is not None else ()
        fn = self._program(
            ("part-gspmd", sig, cap, vocab, dim, world, threshold,
             has_state, mesh),
            lambda: _build_partition_gspmd(sig, vocab, threshold,
                                           has_state, mesh))

        def lift_rows(x):
            return jax.make_array_from_single_device_arrays(
                (vocab,) + tuple(x.shape[1:]),
                NamedSharding(mesh, P("dp") if x.ndim == 1
                              else P("dp", *([None] * (x.ndim - 1)))),
                [x])

        def lift_stream(x):
            return jax.make_array_from_single_device_arrays(
                (world * cap,) + tuple(x.shape[1:]),
                NamedSharding(mesh, P("dp") if x.ndim == 1
                              else P("dp", *([None] * (x.ndim - 1)))),
                [x])

        w_g = lift_rows(stored._data)
        st_g = lift_rows(state_nd._data) if has_state else ()
        res_g = lift_rows(res_in) if threshold is not None else ()
        idx_g = lift_stream(idx)
        rows_g = lift_stream(rows)
        if world > 1:
            _sharding.ALLTOALL_BYTES.inc(cap * 4 + cap * dim * 4)
        _count_dispatch()
        SPARSE_DISPATCHES.inc()
        new_w, new_state, new_res = _SITE.timed(
            fn, w_g, st_g, res_g, idx_g, rows_g, lr, wd,
            jnp.float32(rescale))

        def unlift(x):
            return x.addressable_data(0) if world > 1 else x

        if threshold is not None:
            self._residuals[key] = unlift(new_res)
        return unlift(new_w), (unlift(new_state) if has_state else None)

    def _dispatch_host(self, key, sig, stored, state_nd, threshold,
                       vocab, dim, caps, idxs, rowss, lr, wd, rescale):
        """Multi-process host transport (PR 7 pattern): local
        coalesce+quantize program -> one allgather of the (indices,
        rows) payload -> global coalesce+apply program, deterministic in
        rank order so every rank's replicated table stays
        bit-identical."""
        from ..kvstore_tpu import dist
        from ..executor import _count_dispatch
        has_state = state_nd is not None
        fn_local = self._program(
            ("pre", caps, vocab, dim, threshold),
            lambda: _build_pre(vocab, threshold))
        res_in = self._residual(key, vocab, dim) \
            if threshold is not None else ()
        _count_dispatch()
        SPARSE_DISPATCHES.inc()
        uidx, g, new_res = _SITE.timed(
            fn_local, res_in, tuple(idxs), tuple(rowss))
        if threshold is not None:
            self._residuals[key] = new_res
        # the payload fetch + allgather are the transport's ONE
        # synchronization point per push, the documented host-transport
        # cost (docs/EMBEDDING.md) — the apply below is async again
        head = _np.asarray(uidx, _np.int32)  # analyze: ok(hostsync) host transport payload fetch — the one sync per push
        body = _np.asarray(g, _np.float32)
        payload = head.tobytes() + body.tobytes()
        gathered = dist.allgather_bytes("embpush", payload)
        all_idx, all_rows = [], []
        for buf in gathered:
            n = len(buf) // (4 + 4 * dim)
            all_idx.append(_np.frombuffer(buf[:4 * n], _np.int32))
            all_rows.append(_np.frombuffer(buf[4 * n:], _np.float32)
                            .reshape(n, dim))
        idx_g = _np.concatenate(all_idx)
        rows_g = _np.concatenate(all_rows)
        n = idx_g.shape[0]
        cap_g = pad_length(max(n, 1))
        if cap_g != n:
            idx_g = _np.concatenate(
                [idx_g, _np.full(cap_g - n, vocab, _np.int32)])
            rows_g = _np.concatenate(
                [rows_g, _np.zeros((cap_g - n, dim), _np.float32)])
        fn_apply = self._program(
            ("apply", sig, cap_g, vocab, dim, has_state),
            lambda: _build_apply_only(sig, vocab, has_state))
        _count_dispatch()
        SPARSE_DISPATCHES.inc()
        new_w, new_state = _SITE.timed(
            fn_apply, stored._data,
            state_nd._data if has_state else (),
            jnp.asarray(idx_g), jnp.asarray(rows_g), lr, wd,
            jnp.float32(rescale))
        return new_w, (new_state if has_state else None)


def _build_local(sig, vocab, threshold, has_state):
    from ..aot.store import safe_donate_argnums as _donate

    @partial(jax.jit, donate_argnums=_donate((0, 1, 2)))
    def step(w, state, residual, idxs, rowss, lr, wd, rescale):
        _SITE.note()
        idx = jnp.concatenate(idxs) if len(idxs) > 1 else idxs[0]
        rows = jnp.concatenate(rowss) if len(rowss) > 1 else rowss[0]
        uidx, g = _coalesce(idx, rows, vocab)
        new_res = ()
        if threshold is not None:
            res_rows = jnp.take(residual, uidx, axis=0, mode="fill",
                                fill_value=0)
            g, new_rows = two_bit_quantize(res_rows, g, threshold)
            new_res = residual.at[uidx].set(new_rows, mode="drop")
        new_w, new_state = _sparse_apply(
            sig, w, state if has_state else None, uidx, g, lr, wd,
            rescale)
        return new_w, (new_state if has_state else ()), new_res

    return step


def _build_partition_gspmd(sig, vocab, threshold, has_state, mesh):
    """ONE GSPMD program for the pod-partitioned sparse apply: table /
    state / residual arrive row-sharded over the process 'dp' mesh,
    gradient streams arrive 'dp'-sharded (each rank's slice is its own
    padded contribution), and the global coalesce -> quantize -> lazy
    apply runs as a single launch whose cross-shard gathers/scatters
    XLA lowers to the fabric all-to-all."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..aot.store import safe_donate_argnums as _donate

    def _rows_spec(x):
        return NamedSharding(mesh, P("dp") if x.ndim == 1
                             else P("dp", *([None] * (x.ndim - 1))))

    @partial(jax.jit, donate_argnums=_donate((0, 1, 2)))
    def step(w, state, residual, idx, rows, lr, wd, rescale):
        _SITE.note()
        w = jax.lax.with_sharding_constraint(w, _rows_spec(w))
        if has_state:
            state = jax.lax.with_sharding_constraint(
                state, _rows_spec(state))
        uidx, g = _coalesce(idx, rows, vocab)
        new_res = ()
        if threshold is not None:
            residual = jax.lax.with_sharding_constraint(
                residual, _rows_spec(residual))
            res_rows = jnp.take(residual, uidx, axis=0, mode="fill",
                                fill_value=0)
            g, new_rows = two_bit_quantize(res_rows, g, threshold)
            new_res = residual.at[uidx].set(new_rows, mode="drop")
            new_res = jax.lax.with_sharding_constraint(
                new_res, _rows_spec(new_res))
        new_w, new_state = _sparse_apply(
            sig, w, state if has_state else None, uidx, g, lr, wd,
            rescale)
        new_w = jax.lax.with_sharding_constraint(new_w, _rows_spec(new_w))
        if has_state:
            new_state = jax.lax.with_sharding_constraint(
                new_state, _rows_spec(new_state))
        return new_w, (new_state if has_state else ()), new_res

    return step


def _build_pre(vocab, threshold):
    """Local half of the host transport: coalesce (+ quantize against
    the host-local residual) before anything crosses the wire."""
    from ..aot.store import safe_donate_argnums as _donate

    @partial(jax.jit, donate_argnums=_donate((0,)))
    def pre(residual, idxs, rowss):
        _SITE.note()
        idx = jnp.concatenate(idxs) if len(idxs) > 1 else idxs[0]
        rows = jnp.concatenate(rowss) if len(rowss) > 1 else rowss[0]
        uidx, g = _coalesce(idx, rows, vocab)
        new_res = ()
        if threshold is not None:
            res_rows = jnp.take(residual, uidx, axis=0, mode="fill",
                                fill_value=0)
            g, new_rows = two_bit_quantize(res_rows, g, threshold)
            new_res = residual.at[uidx].set(new_rows, mode="drop")
        return uidx, g, new_res

    return pre


def _build_apply_only(sig, vocab, has_state):
    """Global half of the host transport: coalesce the rank-ordered
    union (already quantized per host) and apply."""
    from ..aot.store import safe_donate_argnums as _donate

    @partial(jax.jit, donate_argnums=_donate((0, 1)))
    def apply_(w, state, idx, rows, lr, wd, rescale):
        _SITE.note()
        uidx, g = _coalesce(idx, rows, vocab)
        new_w, new_state = _sparse_apply(
            sig, w, state if has_state else None, uidx, g, lr, wd,
            rescale)
        return new_w, (new_state if has_state else ())

    return apply_
