"""Row-partition math and device placement for sharded embedding tables.

One embedding table of ``vocab`` rows is row-partitioned two ways at
once (docs/EMBEDDING.md):

* **across devices** (layer 5): when the process sees more than one XLA
  device, the table is laid out over a 1-D ``Mesh`` with axis ``"row"``
  (``NamedSharding((row, None))``) so a compiled lookup lowers to
  gather -> all-to-all/psum over ICI under GSPMD. With one device (the
  CPU test container) placement is the identity and the same programs
  run unsharded.
* **across processes** (layer 6, the ``kvstore='tpu'`` world from
  kvstore_tpu/dist.py): each rank OWNS the contiguous row range
  ``row_range(vocab, rank, world)`` for checkpoint-writing purposes
  (embedding/checkpoint.py: each rank persists its range; any host can
  die). On the CPU multi-process worlds the table itself stays
  replicated-deterministic — every rank applies the identical globally
  coalesced update (engine.py), the same invariant the dense host
  transport keeps for flat buckets.

The split matters: device sharding is a *placement* concern the
compiled programs see; process ownership is a *durability* concern only
the checkpoint path sees. Neither leaks into the other's cache keys.
"""
from __future__ import annotations

import threading

import jax
import numpy as _np

from .. import telemetry as _telemetry

__all__ = ["row_range", "owned_slice", "local_mesh", "table_sharding",
           "place_table", "account_bytes", "EMBED_HBM"]

# table + optimizer state + error-feedback residual bytes currently
# resident for embedding tables, summed over registered keys
# (docs/OBSERVABILITY.md); recsys capacity planning reads this gauge
EMBED_HBM = _telemetry.REGISTRY.gauge(
    "embedding_hbm_bytes",
    "bytes resident for embedding tables (weights + optimizer state + "
    "residuals), summed over tables", unit="bytes")

_LOCK = threading.Lock()
_MESH_CACHE = {}          # n_devices -> Mesh   (guarded by _LOCK)
_HBM_BY_KEY = {}          # key -> bytes        (guarded by _LOCK)


def row_range(vocab, rank, world):
    """The contiguous row range rank ``rank`` owns: ceil-partitioned so
    every rank owns rows (the final rank's range may be short or empty
    when ``world`` does not divide ``vocab``)."""
    if world <= 0:
        raise ValueError("world must be positive")
    per = -(-int(vocab) // int(world))      # ceil division
    lo = min(int(vocab), int(rank) * per)
    hi = min(int(vocab), lo + per)
    return lo, hi


def owned_slice(host_array, rank, world):
    """``host_array[lo:hi]`` for this rank's owned row range."""
    lo, hi = row_range(host_array.shape[0], rank, world)
    return host_array[lo:hi], lo, hi


def local_mesh():
    """The process-local 1-D row mesh, or None when a single device (or
    a multi-process world, where cross-device layout is the kvstore
    transport's concern) makes sharding a no-op."""
    from ..kvstore_tpu import dist
    if dist.world_size() > 1:
        return None
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    with _LOCK:
        mesh = _MESH_CACHE.get(len(devs))
        if mesh is None:
            mesh = jax.sharding.Mesh(_np.asarray(devs), ("row",))
            _MESH_CACHE[len(devs)] = mesh
        return mesh


def table_sharding(mesh):
    """NamedSharding for a (vocab, dim) table: rows over the mesh."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("row", None))  # analyze: ok(sharding) embedding tables ride a dedicated single-axis 'row' mesh (local_mesh above), not the training mesh's named axes


def place_table(arr):
    """Lay a (vocab, dim) jax array out over the local row mesh (the
    identity when there is no mesh or the vocab does not divide)."""
    mesh = local_mesh()
    if mesh is None or arr.ndim < 2:
        return arr
    if arr.shape[0] % mesh.size != 0:
        return arr      # GSPMD wants even row tiles; lookup still works
    return jax.device_put(arr, table_sharding(mesh))


def account_bytes(key, nbytes):
    """Record ``key``'s resident embedding bytes (replaces any previous
    figure for the key) and refresh the ``embedding_hbm_bytes`` gauge."""
    with _LOCK:
        if nbytes:
            _HBM_BY_KEY[key] = int(nbytes)
        else:
            _HBM_BY_KEY.pop(key, None)
        EMBED_HBM.set(sum(_HBM_BY_KEY.values()))
