"""Row-partition math and device placement for sharded embedding tables.

One embedding table of ``vocab`` rows is row-partitioned two ways at
once (docs/EMBEDDING.md):

* **across devices** (layer 5): when the process sees more than one XLA
  device, the table is laid out over a 1-D ``Mesh`` with axis ``"row"``
  (``NamedSharding((row, None))``) so a compiled lookup lowers to
  gather -> all-to-all/psum over ICI under GSPMD. With one device (the
  CPU test container) placement is the identity and the same programs
  run unsharded.
* **across processes** (layer 6, the ``kvstore='tpu'`` world from
  kvstore_tpu/dist.py): each rank OWNS the contiguous row range
  ``row_range(vocab, rank, world)`` for checkpoint-writing purposes
  (embedding/checkpoint.py: each rank persists its range; any host can
  die). On the CPU multi-process worlds the table itself stays
  replicated-deterministic — every rank applies the identical globally
  coalesced update (engine.py), the same invariant the dense host
  transport keeps for flat buckets.

The split matters: device sharding is a *placement* concern the
compiled programs see; process ownership is a *durability* concern only
the checkpoint path sees. Neither leaks into the other's cache keys.
"""
from __future__ import annotations

import os
import threading

import jax
import numpy as _np

from .. import telemetry as _telemetry

__all__ = ["row_range", "owned_slice", "local_mesh", "table_sharding",
           "place_table", "account_bytes", "EMBED_HBM",
           "partition_decision", "process_row_mesh",
           "account_table_bytes", "EMBED_TBL_PER_HOST", "ALLTOALL_BYTES"]

# table + optimizer state + error-feedback residual bytes currently
# resident for embedding tables, summed over registered keys
# (docs/OBSERVABILITY.md); recsys capacity planning reads this gauge
EMBED_HBM = _telemetry.REGISTRY.gauge(
    "embedding_hbm_bytes",
    "bytes resident for embedding tables (weights + optimizer state + "
    "residuals), summed over tables", unit="bytes")
# TABLE weight bytes this host actually holds: a replicated table
# contributes its full (vocab, dim) footprint, a pod-partitioned one
# only its owned row slab — the 1/W capacity-scaling witness the dlrm
# bench gates (docs/EMBEDDING.md)
EMBED_TBL_PER_HOST = _telemetry.REGISTRY.gauge(
    "embedding_table_bytes_per_host",
    "embedding table weight bytes resident on this host (a partitioned "
    "table counts only its owned row slab)", unit="bytes")
# bytes this rank handed to the partitioned lookup/apply all-to-all
# transport (index routing + row return legs; 0 while tables replicate)
ALLTOALL_BYTES = _telemetry.REGISTRY.counter(
    "embedding_alltoall_bytes",
    "bytes this process contributed to partitioned-embedding all-to-all "
    "exchanges (indices out + rows back)", unit="bytes")

_LOCK = threading.Lock()
_MESH_CACHE = {}          # n_devices -> Mesh   (guarded by _LOCK)
_HBM_BY_KEY = {}          # key -> bytes        (guarded by _LOCK)
_TBL_BY_KEY = {}          # key -> table weight bytes (guarded by _LOCK)


def row_range(vocab, rank, world):
    """The contiguous row range rank ``rank`` owns: ceil-partitioned so
    every rank owns rows (the final rank's range may be short or empty
    when ``world`` does not divide ``vocab``)."""
    if world <= 0:
        raise ValueError("world must be positive")
    per = -(-int(vocab) // int(world))      # ceil division
    lo = min(int(vocab), int(rank) * per)
    hi = min(int(vocab), lo + per)
    return lo, hi


def owned_slice(host_array, rank, world):
    """``host_array[lo:hi]`` for this rank's owned row range."""
    lo, hi = row_range(host_array.shape[0], rank, world)
    return host_array[lo:hi], lo, hi


def local_mesh():
    """The process-local 1-D row mesh, or None when a single device (or
    a multi-process world, where cross-device layout is the kvstore
    transport's concern) makes sharding a no-op."""
    from ..kvstore_tpu import dist
    if dist.world_size() > 1:
        return None
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    with _LOCK:
        mesh = _MESH_CACHE.get(len(devs))
        if mesh is None:
            mesh = jax.sharding.Mesh(_np.asarray(devs), ("row",))
            _MESH_CACHE[len(devs)] = mesh
        return mesh


def table_sharding(mesh):
    """NamedSharding for a (vocab, dim) table: rows over the mesh."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("row", None))  # analyze: ok(sharding) embedding tables ride a dedicated single-axis 'row' mesh (local_mesh above), not the training mesh's named axes


def place_table(arr):
    """Lay a (vocab, dim) jax array out over the local row mesh (the
    identity when there is no mesh or the vocab does not divide)."""
    mesh = local_mesh()
    if mesh is None or arr.ndim < 2:
        return arr
    if arr.shape[0] % mesh.size != 0:
        return arr      # GSPMD wants even row tiles; lookup still works
    return jax.device_put(arr, table_sharding(mesh))


def account_bytes(key, nbytes):
    """Record ``key``'s resident embedding bytes (replaces any previous
    figure for the key) and refresh the ``embedding_hbm_bytes`` gauge."""
    with _LOCK:
        if nbytes:
            _HBM_BY_KEY[key] = int(nbytes)
        else:
            _HBM_BY_KEY.pop(key, None)
        EMBED_HBM.set(sum(_HBM_BY_KEY.values()))


def account_table_bytes(key, nbytes):
    """Record ``key``'s table WEIGHT bytes on this host (the slab for a
    partitioned table) and refresh ``embedding_table_bytes_per_host``."""
    with _LOCK:
        if nbytes:
            _TBL_BY_KEY[key] = int(nbytes)
        else:
            _TBL_BY_KEY.pop(key, None)
        EMBED_TBL_PER_HOST.set(sum(_TBL_BY_KEY.values()))


def process_row_mesh():
    """The cross-process 1-D 'dp' mesh partitioned tables ride: one
    device per process (dist.process_mesh), cached so equal meshes share
    program-cache entries."""
    from ..kvstore_tpu import dist
    key = ("proc", dist.world_size())
    with _LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            mesh = _MESH_CACHE[key] = dist.process_mesh()
        return mesh


def partition_decision(vocab, dtype):
    """How a ShardedEmbedding table attaching to a kvstore should lay
    out across the process world (docs/EMBEDDING.md "Multi-host
    partitioning"):

    * ``("partition", world)`` — row-partition into ``world`` equal
      slabs (``row_range``; eligibility guarantees exact division, so
      the bounds equal the checkpoint shards' replicated-world bounds);
    * ``("replicate", slug)``  — stay replicated because the table is
      partition-INELIGIBLE; ``slug`` is the bounded
      ``kvstore_fallbacks`` reason (vocab not divisible by the world /
      non-f32 dtype);
    * ``("replicate", None)``  — partitioning is simply not in play
      (single process and not forced, or ``MXNET_EMBED_PARTITION=0``).

    ``MXNET_EMBED_PARTITION``: ``0`` never partitions, ``1`` forces the
    partitioned code path even in a single-process world (the slab is
    then the whole table — the tier-1 coverage mode), default (auto)
    partitions exactly when the world has more than one process."""
    mode = os.environ.get("MXNET_EMBED_PARTITION", "")
    if mode == "0":
        return "replicate", None
    from ..kvstore_tpu import dist
    world = dist.world_size()
    if world <= 1 and mode != "1":
        return "replicate", None
    if int(vocab) % world != 0:
        return "replicate", "embed_partition_vocab_indivisible"
    if _np.dtype(dtype) != _np.float32:
        return "replicate", "embed_partition_dtype"
    return "partition", world
