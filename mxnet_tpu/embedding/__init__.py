"""mx.embedding — device-sharded embedding tables + a compiled
row_sparse gradient pipeline (docs/EMBEDDING.md).

The recommendation-scale workload (DLRM-style models: embedding-
dominated FLOPs, heavy-tailed index traffic) threaded through every
layer that already exists:

* ``ShardedEmbedding`` (block.py) — gluon block whose table
  row-partitions over the local device mesh (sharding.py) and whose
  lookup is ONE compiled gather program per step (lookup.py);
* ``SparseApplyEngine`` (engine.py) — the kvstore's compiled
  dedup/coalesce -> 2-bit-compress -> cross-host-reduce -> lazy
  sparse-apply program per table, routed automatically by
  ``kv.push`` for row_sparse gradients when the optimizer implements
  ``_fused_sparse_sig`` (SGD, AdaGrad, GroupAdaGrad);
* sharded-table checkpoints (checkpoint.py) — each rank persists its
  owned row range under the PR 7 manifest protocol;
* ``bench.py --mode dlrm`` exercises the whole stack and pins
  ``sparse_dispatches_per_step <= 2`` and zero steady-state retraces.

The symbol-level twin is the ``_contrib_ShardedEmbedding`` op
(ops/nn.py) for compiled module graphs.
"""
from . import sharding
from . import lookup
from . import engine
from . import block
from . import checkpoint
from .sharding import row_range, local_mesh, place_table
from .lookup import lookup as lookup_rows
from .engine import SparseApplyEngine
from .block import ShardedEmbedding
from .checkpoint import (save_tables, load_tables, latest_tables,
                         list_table_tags)

__all__ = ["ShardedEmbedding", "SparseApplyEngine", "row_range",
           "local_mesh", "place_table", "lookup_rows", "save_tables",
           "load_tables", "latest_tables", "list_table_tags",
           "sharding", "lookup", "engine", "block", "checkpoint"]
