"""mx.aot — zero-cold-start deploys (docs/AOT.md).

Two layers, composable:

* **Persistent program cache** — ``MXNET_COMPILE_CACHE_DIR`` makes
  every compiled executable (executor fwd/fwd_bwd, fused fit step,
  kvstore programs, Pallas kernels) survive process restarts on disk;
  a restarted process disk-loads instead of recompiling
  (``aot_cache_hits`` counts the loads).  Auto-enabled at import when
  the knob is set.

* **Warmup manifests** — ``capture()`` in a warmed process dumps every
  program signature; ``warm(manifest, server=..., engine=...)`` in a
  fresh process dispatches all of them (through the cache when
  enabled) BEFORE traffic arrives, so the first request/step sees
  ``coldstart_compiles == 0``.  Programs compiled under ``warm()`` are
  flagged ``warmed`` in ``telemetry.programs()`` to separate deploy
  cost from live compile storms.

Typical deploy::

    # warmed pod, once:
    mx.aot.save(mx.aot.capture(), "model.aot.json")
    # every restart (MXNET_COMPILE_CACHE_DIR shared):
    server = serving.ModelServer(sym, params, ...,
                                 warmup_manifest="model.aot.json")
"""
import logging

from ..telemetry.programs import warming
from . import manifest as _manifest
from . import store
from .manifest import capture, compatible, default_path, load, save
from .store import cache_dir, disable as disable_persistent_cache
from .store import enable as enable_persistent_cache

log = logging.getLogger(__name__)

__all__ = [
    "capture", "save", "load", "warm", "warming", "compatible",
    "default_path", "enable_persistent_cache",
    "disable_persistent_cache", "cache_dir", "stats", "store",
]


def warm(manifest, *, server=None, engine=None, module=None):
    """Pre-compile every program a previous process dispatched.

    ``manifest`` is a path or a ``capture()`` dict.  Targets are the
    objects that own the dispatch sites: a ``serving.ModelServer``
    (warms each replica's bucketed predictors), a
    ``decode.DecodeEngine`` (decode step + caches), a bound
    ``module.Module`` with a fused fit step.  An incompatible manifest
    (version/backend/mesh drift) is skipped with a warning — the
    process simply compiles on first use; deploys never fail here.

    Returns ``{"entries": n, "warmed": k, "skipped": reason|None}``.
    """
    m = _manifest.load(manifest) if isinstance(manifest, str) else manifest
    ok, reason = _manifest.compatible(m)
    if not ok:
        log.warning("aot: manifest incompatible (%s); falling back to "
                    "cold compiles", reason)
        return {"entries": len(_manifest.entries(m)), "warmed": 0,
                "skipped": reason}
    n = 0
    with warming():
        for target in (server, engine, module):
            if target is None:
                continue
            n += int(target.aot_warm(m) or 0)
    from .. import sharding
    mesh = sharding.get_mesh()
    fp = sharding.mesh_fingerprint(mesh) if mesh is not None else None
    store.index_update(_manifest.entries(m), mesh_fingerprint=fp)
    return {"entries": len(_manifest.entries(m)), "warmed": n,
            "skipped": None}


def stats():
    """Cache/warmup counters for quick inspection and bench gates."""
    from ..telemetry.programs import PROGRAMS_WARMED
    return {
        "cache_dir": store.cache_dir(),
        "cache_hits": store.AOT_CACHE_HITS.value,
        "cache_misses": store.AOT_CACHE_MISSES.value,
        "index_errors": store.AOT_INDEX_ERRORS.value,
        "programs_warmed": PROGRAMS_WARMED.value,
    }


# deploys opt in with the env knob alone — no code change needed
enable_persistent_cache()
