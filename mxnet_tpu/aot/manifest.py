"""AOT warmup manifests (docs/AOT.md).

A manifest is a JSON snapshot of every compiled program a running
process dispatched — site, fn_name, full argument signature (treedef +
per-leaf dtype/shape), donation mask — plus a compatibility header
(jax version, backend, device kind, mesh fingerprint, cache dir).
``mx.aot.capture()`` dumps it from a warmed process;
``mx.aot.warm(manifest)`` in a FRESH process AOT-compiles (or, with
``MXNET_COMPILE_CACHE_DIR`` set, disk-loads) every entry before the
process accepts traffic, so the first request/step launches with
``coldstart_compiles == 0``.

Manifests are advisory: an incompatible or stale manifest is skipped
with a warning and the process falls back to compile-on-first-use —
never a hard failure at deploy time.  ``load()`` of a syntactically
broken file does raise (that is an operator error, not drift).
"""
import json
import os

from ..base import MXNetError
from ..telemetry import programs as _programs

FORMAT_VERSION = 1


def _platform():
    import jax
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", str(dev))
    except Exception:
        kind = None
    return jax.default_backend(), kind


def capture(site=None):
    """Snapshot the process's compiled programs into a manifest dict.

    ``site`` filters to one RetraceSite (e.g. ``"executor"``); default
    is every registered program with a recorded signature."""
    from .. import sharding
    from . import store
    backend, kind = _platform()
    import jax
    mesh = sharding.get_mesh()
    fp = sharding.mesh_fingerprint(mesh) if mesh is not None else None
    return {
        "format": FORMAT_VERSION,
        "jax": str(jax.__version__),
        "backend": backend,
        "device_kind": kind,
        "mesh": repr(fp) if fp is not None else None,
        "cache_dir": store.cache_dir(),
        "entries": _programs.export_signatures(site=site),
    }


def save(manifest, path):
    """Write a manifest atomically (tmp + rename)."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load(path):
    """Read and validate a manifest; raises MXNetError on a file that
    is not a manifest (operator error — unlike version drift, which
    ``compatible()`` reports softly)."""
    try:
        with open(os.fspath(path)) as f:
            m = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError("aot: cannot read manifest %s: %s" % (path, e))
    if (not isinstance(m, dict) or "entries" not in m
            or not isinstance(m["entries"], list)):
        raise MXNetError("aot: %s is not an AOT manifest" % (path,))
    return m


def default_path():
    """The ``MXNET_AOT_MANIFEST`` knob: manifest consumed by server /
    engine startup when no explicit path is passed (None = unset)."""
    return os.environ.get("MXNET_AOT_MANIFEST") or None


def compatible(manifest):
    """(ok, reason) — whether warming from this manifest can reuse
    programs in this process.  Soft check: callers log the reason and
    fall back to cold compiles rather than raising."""
    import jax
    from .. import sharding
    if manifest.get("format") != FORMAT_VERSION:
        return False, "manifest format %r != %d" % (
            manifest.get("format"), FORMAT_VERSION)
    if manifest.get("jax") != str(jax.__version__):
        return False, "jax %s != manifest %s" % (
            jax.__version__, manifest.get("jax"))
    backend, _ = _platform()
    if manifest.get("backend") != backend:
        return False, "backend %s != manifest %s" % (
            backend, manifest.get("backend"))
    mesh = sharding.get_mesh()
    fp = sharding.mesh_fingerprint(mesh) if mesh is not None else None
    here = repr(fp) if fp is not None else None
    if manifest.get("mesh") != here:
        return False, "mesh %s != manifest %s" % (
            here, manifest.get("mesh"))
    return True, "ok"


def entries(manifest, site=None):
    """Manifest entries, optionally filtered by RetraceSite."""
    es = manifest.get("entries", [])
    if site is not None:
        es = [e for e in es if e.get("site") == site]
    return es
