"""Persistent compiled-program store (docs/AOT.md).

Wraps jax's persistent compilation cache
(``jax.experimental.compilation_cache``) so every RetraceSite dispatch
— executor fwd/fwd_bwd, the fused fit step, the bucketed kvstore
programs, and the Pallas kernels they embed — serializes its compiled
executable to ``MXNET_COMPILE_CACHE_DIR``.  A restarted process pays
trace + disk-load instead of trace + XLA compile for every program it
has compiled before (``jit_compile_ms`` collapses to trace time; the
``aot_cache_hits`` counter is the witness).

On top of jax's content-addressed files this module keeps its OWN
index (``mx_cache_index.json``): the framework's (site, signature,
mesh-fingerprint) program keys with fn_name / compile_ms / versions,
written by ``mx.aot.capture()``/``warm()``.  The index is pure
bookkeeping — `jax` owns the executables — so corruption or a
version mismatch NEVER breaks a deploy: the index is discarded and
rebuilt, and a corrupt/stale cache entry simply misses (jax validates
its own entries) and falls back to a fresh compile.

Key stability: jax's cache key covers the computation, compile
options, XLA flags and versions.  Processes that should share a cache
must therefore run the same configuration — this module applies the
SAME three cache settings every time, so the framework itself never
forks the key.
"""
import json
import logging
import os
import threading

from .. import telemetry as _telemetry

log = logging.getLogger(__name__)

# bump when the index schema changes: mismatched indexes are discarded
# (never trusted), matching the corruption fallback
FORMAT_VERSION = 1
INDEX_NAME = "mx_cache_index.json"

AOT_CACHE_HITS = _telemetry.REGISTRY.counter(
    "aot_cache_hits", "compiled executables served from the "
    "persistent compilation cache instead of XLA-compiled "
    "(docs/AOT.md)")
AOT_CACHE_MISSES = _telemetry.REGISTRY.counter(
    "aot_cache_misses", "persistent-cache lookups that fell back to a "
    "fresh XLA compile (first compile of a key, or a stale/corrupt "
    "entry)")
AOT_INDEX_ERRORS = _telemetry.REGISTRY.counter(
    "aot_index_errors", "persistent-cache index files discarded as "
    "corrupt or version-mismatched (rebuilt; never fatal)")

_lock = threading.Lock()
_STATE = {"dir": None, "listener": False}


def _jax_version():
    import jax
    return str(jax.__version__)


def cache_dir():
    """The active persistent-cache directory (None = disabled)."""
    return _STATE["dir"]


def donation_safe():
    """False while the persistent cache is enabled: buffer donation and
    disk-loaded executables must not mix.

    jax 0.4.37's DESERIALIZED executables mishandle input/output
    aliasing — a donated program served from a persistent-cache entry
    corrupts its buffers (wrong results, NaN, or a crash, typically
    from the second chained step) on both the CPU and TPU backends.
    Reproducible in pure jax with no framework code involved.  Freshly
    compiled donated programs are correct, and NON-donated programs
    disk-load correctly, so the framework-level guard is: while the
    cache is active, program builders drop donation
    (``safe_donate_argnums``).  Donation changes the program's aliasing
    and therefore its cache key, so donated and non-donated variants
    can never collide in the cache — a guarded process neither writes
    donated entries nor loads one written by an unguarded process.
    """
    return _STATE["dir"] is None


def safe_donate_argnums(argnums):
    """``donate_argnums`` for program builders: the requested positions
    when donation is safe, ``()`` while the persistent cache is active
    (see ``donation_safe``).  Builders run lazily at first use, after
    the import-time env enable, so the decision is current."""
    return tuple(argnums) if donation_safe() else ()


def _on_event(event, **kw):
    # jax monitoring events are the exact hit/miss witnesses: one
    # cache_hits/cache_misses event per persistent-cache lookup
    if event == "/jax/compilation_cache/cache_hits":
        AOT_CACHE_HITS.inc()
    elif event == "/jax/compilation_cache/cache_misses":
        AOT_CACHE_MISSES.inc()


def _install_listener():
    if _STATE["listener"]:
        return
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        _STATE["listener"] = True
    except Exception as e:                     # pragma: no cover
        log.warning("aot: cache hit/miss telemetry unavailable: %s", e)


def _index_path(d):
    return os.path.join(d, INDEX_NAME)


def _fresh_index():
    return {"format": FORMAT_VERSION, "jax": _jax_version(),
            "programs": {}}


def load_index(d=None):
    """The store's program index; a corrupt or version-mismatched file
    is counted, discarded, and replaced by a fresh index (the
    fall-back-to-fresh-compile contract — never raises)."""
    d = d or _STATE["dir"]
    if not d:
        return _fresh_index()
    path = _index_path(d)
    if not os.path.exists(path):
        return _fresh_index()
    try:
        with open(path) as f:
            idx = json.load(f)
        if (not isinstance(idx, dict)
                or idx.get("format") != FORMAT_VERSION
                or idx.get("jax") != _jax_version()
                or not isinstance(idx.get("programs"), dict)):
            raise ValueError("index version/schema mismatch")
        return idx
    except Exception as e:
        AOT_INDEX_ERRORS.inc()
        log.warning("aot: discarding cache index %s (%s); programs "
                    "recompile fresh", path, e)
        return _fresh_index()


def _write_index(d, idx):
    tmp = _index_path(d) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(idx, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, _index_path(d))


def index_update(entries, mesh_fingerprint=None, d=None):
    """Merge program entries (export_signatures rows) into the on-disk
    index under their (site, fn_name, signature, mesh) keys.  Best
    effort — an unwritable cache dir degrades to jax-only caching."""
    d = d or _STATE["dir"]
    if not d:
        return None
    with _lock:
        idx = load_index(d)
        for e in entries:
            key = "|".join([
                e["site"], e["fn_name"],
                str(mesh_fingerprint),
                e.get("treedef", ""),
                ";".join("%s%s" % (s[0], s[1]) if s else "None"
                         for s in e.get("arg_specs", ())),
            ])
            idx["programs"][key] = {
                "site": e["site"], "fn_name": e["fn_name"],
                "compile_ms": e.get("compile_ms"),
                "donated": e.get("donated"),
            }
        try:
            _write_index(d, idx)
        except OSError as e:
            log.warning("aot: cache index not written (%s)", e)
        return idx


def enable(path=None):
    """Turn on the persistent compilation cache.  ``path`` overrides
    the ``MXNET_COMPILE_CACHE_DIR`` knob; with neither set this is a
    no-op returning None (how the package import auto-enables).  Safe
    to call repeatedly; every process that should share the cache
    applies these exact settings so the cache keys agree."""
    d = path or os.environ.get("MXNET_COMPILE_CACHE_DIR")
    if not d:
        return None
    d = os.path.abspath(d)
    os.makedirs(d, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", d)
    # cache every program: the default min-compile-time/entry-size
    # gates would skip exactly the small steady-state programs whose
    # compile storms make cold starts slow
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _install_listener()
    with _lock:
        _STATE["dir"] = d
    # programs jitted before this point kept their donation (safe: they
    # compile in-process, and their aliasing gives them distinct cache
    # keys) — but a process that builds donated programs BEFORE
    # enabling and runs again with the same dir could disk-load them,
    # which jax 0.4.37 corrupts (see donation_safe).  Warn so deploys
    # enable the cache first (the env-var path always does).
    if getattr(_telemetry.programs, "_donated", None):
        log.warning(
            "aot: %d donated program(s) were built before the "
            "persistent cache was enabled; enable the cache before "
            "constructing modules/engines (MXNET_COMPILE_CACHE_DIR "
            "does this at import) so donation is dropped from cached "
            "programs", len(_telemetry.programs._donated))
    # validate (and heal) the index up front so a corrupt file is
    # reported at enable time, not mid-deploy
    idx = load_index(d)
    try:
        _write_index(d, idx)
    except OSError as e:
        log.warning("aot: cache index not written (%s)", e)
    return d


def disable():
    """Tests/teardown: detach the persistent cache."""
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    with _lock:
        _STATE["dir"] = None
