"""Long-tail operator parity: legacy aliases, slice-assign, sparse-named
ops, extra samplers, and small contrib ops.

Reference parity targets:
* legacy CamelCase names — src/operator/tensor/elemwise_binary_broadcast_op
  registrations keep 0.x aliases (_Equal, _Maximum, _Mod, ...)
* _slice_assign / _crop_assign — src/operator/tensor/matrix_op.cc
* _scatter_*_scalar, _scatter_elemwise_div — src/operator/tensor/
  elemwise_binary_scalar_op_extended.cc (sparse-storage-aware variants;
  dense semantics are identical, and dense is our canonical storage)
* cast_storage/_square_sum/_sparse_retain/_sparse_adagrad_update —
  src/operator/tensor/cast_storage.cc, square_sum.cc, sparse_retain.cc,
  optimizer_op.cc (storage-type-specialized kernels; on TPU the registry
  versions are dense-semantics, mxnet_tpu.ndarray.sparse holds the
  stype-preserving frontend)
* ftml_update — src/operator/optimizer_op.cc FTMLUpdate
* hard_sigmoid — src/operator/tensor/elemwise_unary_op_basic.cc
* negative-binomial samplers — src/operator/random/sample_op.cc
* _contrib_div_sqrt_dim — src/operator/contrib/transformer.cc
* _contrib_count_sketch — src/operator/contrib/count_sketch.cc
* IdentityAttachKLSparseReg — src/operator/identity_attach_KL_sparse_reg.cc
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, add_alias, current_op_context
from .nn import needs_rng


# ----------------------------------------------------------------------
# legacy CamelCase aliases (reference keeps these for 0.x graphs)
# ----------------------------------------------------------------------
for _canon, _legacy in [
        ("broadcast_equal", ("_Equal",)),
        ("broadcast_not_equal", ("_Not_Equal",)),
        ("broadcast_greater", ("_Greater",)),
        ("broadcast_greater_equal", ("_Greater_Equal",)),
        ("broadcast_lesser", ("_Lesser",)),
        ("broadcast_lesser_equal", ("_Lesser_Equal",)),
        ("broadcast_logical_and", ("_Logical_And",)),
        ("broadcast_logical_or", ("_Logical_Or",)),
        ("broadcast_logical_xor", ("_Logical_Xor",)),
        ("broadcast_maximum", ("_Maximum",)),
        ("broadcast_minimum", ("_Minimum",)),
        ("broadcast_mod", ("_Mod",)),
        ("broadcast_hypot", ("_Hypot",)),
        ("_equal_scalar", ("_EqualScalar",)),
        ("_not_equal_scalar", ("_NotEqualScalar",)),
        ("_greater_scalar", ("_GreaterScalar",)),
        ("_greater_equal_scalar", ("_GreaterEqualScalar",)),
        ("_lesser_scalar", ("_LesserScalar",)),
        ("_lesser_equal_scalar", ("_LesserEqualScalar",)),
]:
    add_alias(_canon, *_legacy)


def _defscalar_logical(name, fn, aliases=()):
    def impl(data, *, scalar=0.0):
        s = jnp.asarray(scalar, dtype=data.dtype)
        return fn(data, s).astype(data.dtype)
    impl.__name__ = name
    register(name, aliases=aliases)(impl)


_defscalar_logical("_logical_and_scalar", jnp.logical_and,
                   aliases=("_LogicalAndScalar",))
_defscalar_logical("_logical_or_scalar", jnp.logical_or,
                   aliases=("_LogicalOrScalar",))
_defscalar_logical("_logical_xor_scalar", jnp.logical_xor,
                   aliases=("_LogicalXorScalar",))


@register("_hypot_scalar", aliases=("_HypotScalar",))
def hypot_scalar(data, *, scalar=0.0):
    return jnp.hypot(data, jnp.asarray(scalar, dtype=data.dtype))


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    """Linear approximation of sigmoid: clip(alpha*x + beta, 0, 1)
    (ref elemwise_unary_op_basic.cc hard_sigmoid)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


# ----------------------------------------------------------------------
# slice assign (matrix_op.cc _slice_assign / _crop_assign)
# ----------------------------------------------------------------------
def _assign_index(shape, begin, end, step):
    step = tuple(step) if step else ()
    idx = []
    for ax in range(len(begin)):
        b = begin[ax]
        e = end[ax]
        s = step[ax] if ax < len(step) and step[ax] is not None else 1
        idx.append(slice(b, e, s))
    idx.extend(slice(None) for _ in range(len(begin), len(shape)))
    return tuple(idx)


@register("_slice_assign", aliases=("_crop_assign",))
def slice_assign(lhs, rhs, *, begin, end, step=()):
    """Return lhs with lhs[begin:end:step] = rhs (functional in-place
    assignment; the eager frontend writes the result back)."""
    return lhs.at[_assign_index(lhs.shape, begin, end, step)].set(
        rhs.astype(lhs.dtype))


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def slice_assign_scalar(data, *, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_assign_index(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, dtype=data.dtype))


# ----------------------------------------------------------------------
# scatter_* — storage-fallback arithmetic (dense semantics identical)
# ----------------------------------------------------------------------
@register("_scatter_plus_scalar")
def scatter_plus_scalar(data, *, scalar=1.0):
    return data + jnp.asarray(scalar, dtype=data.dtype)


@register("_scatter_minus_scalar")
def scatter_minus_scalar(data, *, scalar=1.0):
    return data - jnp.asarray(scalar, dtype=data.dtype)


@register("_scatter_elemwise_div")
def scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


@register("_identity_with_attr_like_rhs")
def identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs carrying rhs's storage attrs (used by the reference
    in sparse gradient graphs, elemwise_unary_op_basic.cc)."""
    return lhs


# ----------------------------------------------------------------------
# sparse-named registry ops (dense semantics; stype-preserving frontend
# lives in mxnet_tpu.ndarray.sparse)
# ----------------------------------------------------------------------
@register("cast_storage")
def cast_storage(data, *, stype="default"):
    if stype not in ("default", "row_sparse", "csr"):
        raise ValueError("unknown storage type %r" % (stype,))
    return data


@register("_square_sum", aliases=("square_sum",))
def square_sum(data, *, axis=None, keepdims=False, exclude=False):
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else (
        None if axis is None else (int(axis),))
    if exclude and ax is not None:
        ax = tuple(i for i in range(data.ndim) if i not in
                   tuple(a % data.ndim for a in ax))
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


@register("_sparse_retain", aliases=("sparse_retain",))
def sparse_retain(data, indices):
    """Keep only the listed rows of data, zeroing the rest
    (ref sparse_retain-inl.h; dense-storage semantics)."""
    rows = indices.astype(jnp.int32)
    keep = jnp.zeros((data.shape[0],), dtype=bool).at[rows].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("_sparse_adagrad_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("history", 1),))
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad update (ref optimizer_op-inl.h AdagradDnsRspDnsKernel;
    dense rows here — zero-grad rows are naturally untouched since their
    accumulated square stays zero). Same formula as the row-sliced
    frontend in ndarray/sparse.py sparse_adagrad_update: the history
    accumulates the pure (clipped) gradient square, epsilon sits inside
    the sqrt, and wd decays decoupled from the accumulator."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w = weight.astype(jnp.float32)
    new_hist = history.astype(jnp.float32) + jnp.square(g)
    new_w = w - lr * (g / jnp.sqrt(new_hist + epsilon) + wd * w)
    return new_w.astype(weight.dtype), new_hist.astype(history.dtype)


@register("ftml_update", num_outputs=4, num_visible_outputs=1,
          mutate_inputs=(("d", 1), ("v", 2), ("z", 3)))
def ftml_update(weight, grad, d, v, z, t=None, *, lr, beta1=0.6,
                beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    """Follow The Moving Leader update (ref optimizer_op.cc FTMLUpdate).
    The step count ``t`` is a TENSOR input (python ints auto-convert),
    not a static attr — an attr would force one fresh XLA compile per
    optimizer step in the eager dispatch cache."""
    g = grad.astype(jnp.float32) * rescale_grad + wd * weight.astype(
        jnp.float32)
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    tf = jnp.asarray(1.0 if t is None else t, jnp.float32)
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    d_new = ((1.0 - jnp.power(beta1, tf)) / lr
             * (jnp.sqrt(new_v / (1.0 - jnp.power(beta2, tf))) + epsilon))
    sigma = d_new - beta1 * d
    new_z = beta1 * z + (1.0 - beta1) * g - sigma * weight.astype(jnp.float32)
    new_w = -new_z / d_new
    return (new_w.astype(weight.dtype), d_new, new_v, new_z)


# ----------------------------------------------------------------------
# negative-binomial samplers (sample_op.cc): NB as a Gamma-Poisson mixture
# ----------------------------------------------------------------------
def _neg_binomial(key, k, p, shape, dtype):
    """X ~ NB(k, p): lam ~ Gamma(k, scale=(1-p)/p), X ~ Poisson(lam)."""
    kg, kp = jax.random.split(key)
    k = jnp.asarray(k, jnp.float32)
    p = jnp.asarray(p, jnp.float32)
    scale = (1.0 - p) / jnp.maximum(p, 1e-12)
    lam = jax.random.gamma(kg, jnp.broadcast_to(k, shape)) * scale
    return jax.random.poisson(kp, lam).astype(dtype)


def _gen_neg_binomial(key, mu, alpha, shape, dtype):
    """Generalized NB with mean mu, dispersion alpha: k=1/alpha,
    p=1/(1+alpha*mu) — same Gamma-Poisson mixture."""
    kg, kp = jax.random.split(key)
    mu = jnp.asarray(mu, jnp.float32)
    alpha = jnp.asarray(alpha, jnp.float32)
    k = 1.0 / jnp.maximum(alpha, 1e-12)
    lam = jax.random.gamma(kg, jnp.broadcast_to(k, shape)) * (alpha * mu)
    return jax.random.poisson(kp, lam).astype(dtype)


@register("_random_negative_binomial", aliases=("random_negative_binomial",
                                                "negative_binomial"))
@needs_rng
def random_negative_binomial(*, k=1, p=1.0, shape=(), dtype="float32",
                             ctx=None):
    key = current_op_context().next_rng_key()
    return _neg_binomial(key, k, p, tuple(shape), dtype or "float32")


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",
                   "generalized_negative_binomial"))
@needs_rng
def random_generalized_negative_binomial(*, mu=1.0, alpha=1.0, shape=(),
                                         dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return _gen_neg_binomial(key, mu, alpha, tuple(shape),
                             dtype or "float32")


def _row_shape(param, shape):
    sshape = tuple(shape) if isinstance(shape, (tuple, list)) else (
        (int(shape),) if shape else ())
    return param.shape + sshape


@register("_sample_exponential", aliases=("sample_exponential",))
@needs_rng
def sample_exponential(lam, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    out_shape = _row_shape(lam, shape)
    e = jax.random.exponential(key, out_shape)
    return (e / lam.reshape(lam.shape + (1,) * (len(out_shape)
                                                - lam.ndim))).astype(
        dtype or "float32")


@register("_sample_gamma", aliases=("sample_gamma",))
@needs_rng
def sample_gamma(alpha, beta, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    out_shape = _row_shape(alpha, shape)
    ex = alpha.reshape(alpha.shape + (1,) * (len(out_shape) - alpha.ndim))
    g = jax.random.gamma(key, jnp.broadcast_to(ex, out_shape))
    return (g * beta.reshape(ex.shape)).astype(dtype or "float32")


@register("_sample_poisson", aliases=("sample_poisson",))
@needs_rng
def sample_poisson(lam, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    out_shape = _row_shape(lam, shape)
    ex = lam.reshape(lam.shape + (1,) * (len(out_shape) - lam.ndim))
    return jax.random.poisson(key, jnp.broadcast_to(ex, out_shape)).astype(
        dtype or "float32")


@register("_sample_negative_binomial", aliases=("sample_negative_binomial",))
@needs_rng
def sample_negative_binomial(k, p, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    out_shape = _row_shape(k, shape)
    ex = k.reshape(k.shape + (1,) * (len(out_shape) - k.ndim))
    return _neg_binomial(key, jnp.broadcast_to(ex, out_shape),
                         jnp.broadcast_to(p.reshape(ex.shape), out_shape),
                         out_shape, dtype or "float32")


@register("_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",))
@needs_rng
def sample_generalized_negative_binomial(mu, alpha, *, shape=(),
                                         dtype="float32"):
    key = current_op_context().next_rng_key()
    out_shape = _row_shape(mu, shape)
    ex = mu.reshape(mu.shape + (1,) * (len(out_shape) - mu.ndim))
    return _gen_neg_binomial(key, jnp.broadcast_to(ex, out_shape),
                             jnp.broadcast_to(alpha.reshape(ex.shape),
                                              out_shape),
                             out_shape, dtype or "float32")


# ----------------------------------------------------------------------
# small contrib ops
# ----------------------------------------------------------------------
@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data):
    """data / sqrt(last_dim) — attention-logit scaling helper
    (ref src/operator/contrib/transformer.cc)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """Count-sketch projection: out[n, h[i]] += s[i] * data[n, i]
    (ref src/operator/contrib/count_sketch.cc). One XLA scatter-add
    replaces the reference's hand-tiled CUDA kernel; the
    processing_batch_size knob is accepted for API parity but moot."""
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    out = jnp.zeros(data.shape[:-1] + (int(out_dim),), dtype=data.dtype)
    return out.at[..., idx].add(data * sign)


@jax.custom_vjp
def _identity_plus_grad(x, kl):
    return x


def _identity_plus_grad_fwd(x, kl):
    return x, kl


def _identity_plus_grad_bwd(kl, g):
    return (g + kl.astype(g.dtype), jnp.zeros_like(kl))


_identity_plus_grad.defvjp(_identity_plus_grad_fwd, _identity_plus_grad_bwd)


@register("IdentityAttachKLSparseReg", num_outputs=2,
          num_visible_outputs=1, mutate_inputs=(("moving_avg", 1),))
def identity_attach_kl_sparse_reg(data, moving_avg=None, *,
                                  sparseness_target=0.1, penalty=0.001,
                                  momentum=0.9):
    """Identity forward that attaches a KL-sparsity penalty gradient
    (ref identity_attach_KL_sparse_reg-inl.h). Data flattens to
    (batch, dim); the PER-UNIT mean activation feeds a momentum moving
    average (aux state, shape (dim,)), and backward adds
    penalty * (-rho/avg + (1-rho)/(1-avg)) per unit — the reference
    updates the moving average in Backward, so the update only happens
    in training mode here."""
    from .registry import current_op_context
    batch = data.shape[0]
    dim = 1
    for d in data.shape[1:]:
        dim *= int(d)
    dim = max(dim, 1)
    if moving_avg is None:
        moving_avg = jnp.full((dim,), sparseness_target, dtype=jnp.float32)
    rho_hat = data.astype(jnp.float32).reshape(batch, dim).mean(axis=0)
    if current_op_context().is_train:
        new_avg = momentum * moving_avg + (1.0 - momentum) * rho_hat
    else:
        new_avg = moving_avg
    avg = lax.stop_gradient(new_avg.astype(jnp.float32))
    rho = sparseness_target
    kl = penalty * (-rho / jnp.maximum(avg, 1e-12)
                    + (1.0 - rho) / jnp.maximum(1.0 - avg, 1e-12))
    kl_full = jnp.broadcast_to(
        kl.reshape((1,) + data.shape[1:]), data.shape)
    out = _identity_plus_grad(data, kl_full)
    return out, new_avg.astype(moving_avg.dtype)


# contrib aliases for ops registered elsewhere
add_alias("_contrib_ctc_loss", "_contrib_CTCLoss")
add_alias("_contrib_box_nms", "_contrib_box_non_maximum_suppression")
add_alias("Embedding", "_contrib_SparseEmbedding")
