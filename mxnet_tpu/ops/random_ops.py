"""Random sampling operators.

Reference parity: src/operator/random/sample_op.cc (_random_uniform,
_random_normal, _random_gamma, …) and multinomial sampling. TPU-native:
counter-based JAX PRNG keys threaded through OpContext (replaces the
reference's per-device cuRAND resource, src/resource.cc:87).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, current_op_context
from .nn import needs_rng


@register("_random_uniform", aliases=("random_uniform", "uniform"))
@needs_rng
def random_uniform(*, low=0.0, high=1.0, shape=(), dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return jax.random.uniform(key, tuple(shape), minval=low, maxval=high,
                              dtype=dtype or "float32")


@register("_random_normal", aliases=("random_normal", "normal"))
@needs_rng
def random_normal(*, loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return (loc + scale * jax.random.normal(key, tuple(shape))).astype(dtype or "float32")


@register("_random_gamma", aliases=("random_gamma",))
@needs_rng
def random_gamma(*, alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return (jax.random.gamma(key, alpha, tuple(shape)) * beta).astype(dtype or "float32")


@register("_random_exponential", aliases=("random_exponential",))
@needs_rng
def random_exponential(*, lam=1.0, shape=(), dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return (jax.random.exponential(key, tuple(shape)) / lam).astype(dtype or "float32")


@register("_random_poisson", aliases=("random_poisson",))
@needs_rng
def random_poisson(*, lam=1.0, shape=(), dtype="float32", ctx=None):
    key = current_op_context().next_rng_key()
    return jax.random.poisson(key, lam, tuple(shape)).astype(dtype or "float32")


@register("_random_randint", aliases=("random_randint",))
@needs_rng
def random_randint(*, low=0, high=1, shape=(), dtype="int32", ctx=None):
    key = current_op_context().next_rng_key()
    return jax.random.randint(key, tuple(shape), int(low), int(high),
                              dtype=dtype or "int32")


@register("_sample_uniform", aliases=("sample_uniform",))
@needs_rng
def sample_uniform(low, high, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    sshape = tuple(shape) if shape else ()
    u = jax.random.uniform(key, low.shape + sshape)
    ex = low.reshape(low.shape + (1,) * len(sshape))
    return (ex + u * (high - low).reshape(ex.shape)).astype(dtype or "float32")


@register("_sample_normal", aliases=("sample_normal",))
@needs_rng
def sample_normal(mu, sigma, *, shape=(), dtype="float32"):
    key = current_op_context().next_rng_key()
    sshape = tuple(shape) if shape else ()
    z = jax.random.normal(key, mu.shape + sshape)
    ex = mu.reshape(mu.shape + (1,) * len(sshape))
    return (ex + z * sigma.reshape(ex.shape)).astype(dtype or "float32")


@register("_sample_multinomial", aliases=("sample_multinomial",))
@needs_rng
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32"):
    """Categorical sampling from probability rows
    (ref src/operator/random/sample_multinomial_op.cc)."""
    key = current_op_context().next_rng_key()
    n = 1
    for s in (shape if isinstance(shape, tuple) else (shape,)) if shape else ():
        n *= int(s)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    sshape = tuple(shape) if isinstance(shape, tuple) else ((shape,) if shape else ())
    out_shape = data.shape[:-1] + sshape
    samples = jax.random.categorical(
        key, logits, axis=-1,
        shape=(sshape + data.shape[:-1]) if sshape else data.shape[:-1])
    if sshape:
        samples = jnp.moveaxis(samples.reshape(sshape + data.shape[:-1]),
                               tuple(range(len(sshape))),
                               tuple(range(-len(sshape), 0)))
    return samples.astype(dtype)


@register("_shuffle", aliases=("shuffle",))
@needs_rng
def shuffle(data):
    key = current_op_context().next_rng_key()
    return jax.random.permutation(key, data, axis=0)
