"""INT8 quantization operators.

Reference parity: src/operator/quantization/ (quantize.cc,
dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc, quantized_pooling.cc,
quantized_flatten.cc). TPU-native: int8 tensors with explicit
(min, max) range companions; quantized conv/FC accumulate in int32 via
``preferred_element_type`` so the MXU runs the 8-bit multiplies. The
range calculus matches the reference: int8 is symmetric around 0
(scale = 127 / max|range|), int32 accumulators carry the product of the
input scales.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_INT8_MAX = 127.0
_INT32_MAX = 2147483647.0


def _range_scale(min_r, max_r):
    # symmetric int8 quantization (reference quantize.cc int8 branch)
    abs_max = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return _INT8_MAX / jnp.maximum(abs_max, 1e-30)


@register("_contrib_quantize", aliases=("quantize",), num_outputs=3)
def quantize(data, min_range, max_range, *, out_type="int8"):
    """fp32 -> int8 with the given range; returns (q, min, max)
    (reference quantize.cc)."""
    if out_type != "int8":
        raise NotImplementedError("only int8 quantization is supported "
                                  "(reference also has uint8)")
    scale = _range_scale(min_range, max_range)
    q = jnp.clip(jnp.rint(data * scale), -_INT8_MAX, _INT8_MAX)
    abs_max = _INT8_MAX / scale
    return q.astype(jnp.int8), -abs_max.reshape(()), abs_max.reshape(())


@register("_contrib_dequantize", aliases=("dequantize",))
def dequantize(data, min_range, max_range, *, out_type="float32"):
    """int8/int32 -> fp32 (reference dequantize.cc)."""
    imax = _INT8_MAX if data.dtype == jnp.int8 else _INT32_MAX
    abs_max = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * (abs_max / imax)


@register("_contrib_requantize", aliases=("requantize",), num_outputs=3)
def requantize(data, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None):
    """int32 -> int8, rescaling into the calibrated range (reference
    requantize.cc; with no calib range the actual range is used)."""
    f32 = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / _INT32_MAX)
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    else:
        hi = jnp.max(jnp.abs(f32))
        lo = -hi
    scale = _range_scale(lo, hi)
    q = jnp.clip(jnp.rint(f32 * scale), -_INT8_MAX, _INT8_MAX)
    abs_max = _INT8_MAX / scale
    return q.astype(jnp.int8), -abs_max.reshape(()), abs_max.reshape(())


def _in_scales(min_d, max_d, min_w, max_w):
    sd = _range_scale(min_d, max_d)
    sw = _range_scale(min_w, max_w)
    # int32 accumulator range corresponds to INT32_MAX / (sd*sw)
    abs_out = _INT32_MAX / (sd * sw)
    return -abs_out.reshape(()), abs_out.reshape(())


@register("_contrib_quantized_conv", aliases=("quantized_conv",),
          num_outputs=3)
def quantized_conv(data, weight, min_data, max_data, min_weight, max_weight,
                   *, kernel, num_filter, stride=(), dilate=(), pad=(),
                   num_group=1, no_bias=True, layout=None):
    """int8 conv with int32 accumulation (reference quantized_conv.cc);
    returns (int32 out, min_out, max_out)."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data.ndim == 4 else ("NCH", "OIH", "NCH"))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
        preferred_element_type=jnp.int32)
    lo, hi = _in_scales(min_data, max_data, min_weight, max_weight)
    return out, lo, hi


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), num_outputs=3)
def quantized_fully_connected(data, weight, min_data, max_data, min_weight,
                              max_weight, *, num_hidden, no_bias=True,
                              flatten=True):
    """int8 FC with int32 accumulation (reference
    quantized_fully_connected.cc)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    out = lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    lo, hi = _in_scales(min_data, max_data, min_weight, max_weight)
    return out, lo, hi


@register("_contrib_quantized_pooling", aliases=("quantized_pooling",),
          num_outputs=3)
def quantized_pooling(data, min_data, max_data, *, kernel, pool_type="max",
                      stride=(), pad=(), global_pool=False,
                      pooling_convention="valid"):
    """int8 max/avg pooling — range passes through (reference
    quantized_pooling.cc)."""
    from .nn import pooling
    out = pooling(data.astype(jnp.float32), kernel=kernel,
                  pool_type=pool_type, stride=stride, pad=pad,
                  global_pool=global_pool,
                  pooling_convention=pooling_convention)
    return out.astype(data.dtype), min_data.reshape(()), max_data.reshape(())


@register("_contrib_quantized_flatten", aliases=("quantized_flatten",),
          num_outputs=3)
def quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1), min_data.reshape(()),
            max_data.reshape(()))
