"""INT8/UINT8 quantization operators.

Reference parity: src/operator/quantization/ (quantize-inl.h:44-99,
dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc, quantized_pooling.cc,
quantized_flatten.cc). TPU-native: 8-bit tensors with explicit
(min, max) range companions; quantized conv/FC accumulate in int32 via
``preferred_element_type`` so the MXU runs the 8-bit multiplies. Range
calculus matches the reference: int8 is zero-centered symmetric
(quantize_zero_centered, scale = 127 / max|range|); uint8 is AFFINE
(quantize_unsigned: [min,max] -> [0,255], zero point = -min·scale).
Mixed uint8-activation × int8-weight conv/FC (the reference's deployed
combination) fold the activation zero point back in as an exact int32
correction term computed from a ones-conv of the weights.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_INT8_MAX = 127.0
_UINT8_MAX = 255.0
_INT32_MAX = 2147483647.0


def _range_scale(min_r, max_r):
    # symmetric int8 quantization (reference quantize_zero_centered)
    abs_max = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return _INT8_MAX / jnp.maximum(abs_max, 1e-30)


@register("_contrib_quantize", aliases=("quantize",), num_outputs=3)
def quantize(data, min_range, max_range, *, out_type="int8"):
    """fp32 -> int8/uint8 with the given range; returns (q, min, max)
    (reference quantize-inl.h:44-99; uint8 keeps the ASYMMETRIC input
    range, int8 re-centers it symmetrically)."""
    if out_type == "int8":
        scale = _range_scale(min_range, max_range)
        q = jnp.clip(jnp.rint(data * scale), -_INT8_MAX, _INT8_MAX)
        abs_max = _INT8_MAX / scale
        return (q.astype(jnp.int8), -abs_max.reshape((1,)),
            abs_max.reshape((1,)))
    if out_type == "uint8":
        lo = jnp.asarray(min_range, jnp.float32).reshape(())
        hi = jnp.asarray(max_range, jnp.float32).reshape(())
        scale = _UINT8_MAX / jnp.maximum(hi - lo, 1e-30)
        q = jnp.clip(jnp.rint((data - lo) * scale), 0.0, _UINT8_MAX)
        return q.astype(jnp.uint8), lo.reshape((1,)), hi.reshape((1,))
    raise ValueError("quantize: out_type must be 'int8' or 'uint8', "
                     "got %r (reference quantize-inl.h)" % (out_type,))


@register("_contrib_dequantize", aliases=("dequantize",))
def dequantize(data, min_range, max_range, *, out_type="float32"):
    """int8/uint8/int32 -> fp32 (reference dequantize.cc). uint8 is
    affine (q/scale + min); int8/int32 symmetric."""
    if data.dtype == jnp.uint8:
        lo = jnp.asarray(min_range, jnp.float32)
        hi = jnp.asarray(max_range, jnp.float32)
        scale = _UINT8_MAX / jnp.maximum(hi - lo, 1e-30)
        return data.astype(jnp.float32) / scale + lo
    imax = _INT8_MAX if data.dtype == jnp.int8 else _INT32_MAX
    abs_max = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * (abs_max / imax)


@register("_contrib_requantize", aliases=("requantize",), num_outputs=3)
def requantize(data, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None, out_type="int8"):
    """int32 -> int8/uint8, rescaling into the calibrated range
    (reference requantize.cc; with no calib range the actual range is
    used)."""
    f32 = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / _INT32_MAX)
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    else:
        hi = jnp.max(jnp.abs(f32))
        lo = -hi
    if out_type == "uint8":
        scale = _UINT8_MAX / jnp.maximum(hi - lo, 1e-30)
        q = jnp.clip(jnp.rint((f32 - lo) * scale), 0.0, _UINT8_MAX)
        return q.astype(jnp.uint8), lo.reshape((1,)), hi.reshape((1,))
    scale = _range_scale(lo, hi)
    q = jnp.clip(jnp.rint(f32 * scale), -_INT8_MAX, _INT8_MAX)
    abs_max = _INT8_MAX / scale
    return (q.astype(jnp.int8), -abs_max.reshape((1,)),
            abs_max.reshape((1,)))


def _data_scale(data_dtype, min_d, max_d):
    if data_dtype == jnp.uint8:
        # affine uint8 activation scale (reference quantize_unsigned)
        return _UINT8_MAX / jnp.maximum(
            jnp.asarray(max_d, jnp.float32) - jnp.asarray(min_d, jnp.float32),
            1e-30)
    return _range_scale(min_d, max_d)


def _in_scales(data_dtype, min_d, max_d, min_w, max_w):
    sd = _data_scale(data_dtype, min_d, max_d)
    sw = _range_scale(min_w, max_w)
    # int32 accumulator range corresponds to INT32_MAX / (sd*sw)
    abs_out = _INT32_MAX / (sd * sw)
    return -abs_out.reshape((1,)), abs_out.reshape((1,))


@register("_contrib_quantized_conv", aliases=("quantized_conv",),
          num_outputs=3)
def quantized_conv(data, weight, min_data, max_data, min_weight, max_weight,
                   *, kernel, num_filter, stride=(), dilate=(), pad=(),
                   num_group=1, no_bias=True, layout=None):
    """8-bit conv with int32 accumulation (reference quantized_conv.cc);
    returns (int32 out, min_out, max_out). uint8 activations (affine,
    zero point zp = -min·scale) fold back exactly: conv(q-zp, w) =
    conv(q, w) + min·s_d·conv(1, w), where conv(1, w) is one batch-1
    ones-convolution capturing the per-position weight sums (border
    positions included)."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data.ndim == 4 else ("NCH", "OIH", "NCH"))
    kw = dict(window_strides=stride,
              padding=[(p, p) for p in pad],
              rhs_dilation=dilate,
              dimension_numbers=dn,
              feature_group_count=int(num_group))
    if data.dtype == jnp.uint8:
        # mixed uint8×int8 operands: XLA convs need one dtype — widen to
        # int32 (exact; the int8-MXU fast path needs matching int8s)
        out = lax.conv_general_dilated(
            data.astype(jnp.int32), weight.astype(jnp.int32),
            preferred_element_type=jnp.int32, **kw)
        sd = _data_scale(jnp.uint8, min_data, max_data)
        zp_f = jnp.asarray(min_data, jnp.float32) * sd   # q ≈ (x-min)·sd
        ones = jnp.ones((1,) + data.shape[1:], jnp.float32)
        wsum = lax.conv_general_dilated(ones, weight.astype(jnp.float32),
                                        **kw)
        out = out + jnp.rint(zp_f * wsum).astype(jnp.int32)
    else:
        out = lax.conv_general_dilated(data, weight,
                                       preferred_element_type=jnp.int32,
                                       **kw)
    lo, hi = _in_scales(data.dtype, min_data, max_data, min_weight,
                        max_weight)
    return out, lo, hi


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), num_outputs=3)
def quantized_fully_connected(data, weight, min_data, max_data, min_weight,
                              max_weight, *, num_hidden, no_bias=True,
                              flatten=True):
    """8-bit FC with int32 accumulation (reference
    quantized_fully_connected.cc); uint8 activations fold their zero
    point back via the per-unit weight sums."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    if data.dtype == jnp.uint8:
        out = lax.dot_general(
            x.astype(jnp.int32), weight.astype(jnp.int32),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        sd = _data_scale(jnp.uint8, min_data, max_data)
        zp_f = jnp.asarray(min_data, jnp.float32) * sd
        wsum = jnp.sum(weight.astype(jnp.float32), axis=1)
        out = out + jnp.rint(zp_f * wsum).astype(jnp.int32)
    else:
        out = lax.dot_general(
            x, weight, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
    lo, hi = _in_scales(data.dtype, min_data, max_data, min_weight,
                        max_weight)
    return out, lo, hi


@register("_contrib_quantized_pooling", aliases=("quantized_pooling",),
          num_outputs=3)
def quantized_pooling(data, min_data, max_data, *, kernel, pool_type="max",
                      stride=(), pad=(), global_pool=False,
                      pooling_convention="valid"):
    """int8 max/avg pooling — range passes through (reference
    quantized_pooling.cc)."""
    from .nn import pooling
    out = pooling(data.astype(jnp.float32), kernel=kernel,
                  pool_type=pool_type, stride=stride, pad=pad,
                  global_pool=global_pool,
                  pooling_convention=pooling_convention)
    return (out.astype(data.dtype),
            jnp.asarray(min_data, jnp.float32).reshape((1,)),
            jnp.asarray(max_data, jnp.float32).reshape((1,)))


@register("_contrib_quantized_flatten", aliases=("quantized_flatten",),
          num_outputs=3)
def quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1),
            jnp.asarray(min_data, jnp.float32).reshape((1,)),
            jnp.asarray(max_data, jnp.float32).reshape((1,)))
