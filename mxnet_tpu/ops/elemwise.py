"""Elementwise, broadcast, and reduction operators.

Reference parity: src/operator/tensor/elemwise_binary_broadcast_op_basic.cc,
elemwise_unary_op_basic.cc, broadcast_reduce_op_value.cc. All impls are pure
jnp — XLA fuses chains of these into single kernels, which replaces the
reference's mshadow expression templates and manual kernel fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_EPS = 1e-12


# ----------------------------------------------------------------------
# broadcast binary
# ----------------------------------------------------------------------
def _defbinary(name, fn, aliases=()):
    def impl(lhs, rhs):
        return fn(lhs, rhs)
    impl.__name__ = name
    impl.__doc__ = "Broadcast binary op %s (ref src/operator/tensor/)" % name
    register(name, aliases=aliases)(impl)


_defbinary("broadcast_add", jnp.add, aliases=("broadcast_plus", "elemwise_add", "_add", "_plus", "_Plus"))
_defbinary("broadcast_sub", jnp.subtract, aliases=("broadcast_minus", "elemwise_sub", "_sub", "_minus", "_Minus"))
_defbinary("broadcast_mul", jnp.multiply, aliases=("elemwise_mul", "_mul", "_Mul"))
def _ref_div(a, b):
    """Reference division semantics: integer inputs keep the integer dtype
    with C-style truncation (mshadow's `/` lowers to C `/`); floats divide
    normally. jnp.divide alone would promote ints to float."""
    out_dtype = jnp.result_type(a, b)
    q = jnp.divide(a, b)
    if jnp.issubdtype(out_dtype, jnp.integer):
        return jnp.trunc(q).astype(out_dtype)
    return q


_defbinary("broadcast_div", _ref_div, aliases=("elemwise_div", "_div", "_Div"))
_defbinary("broadcast_mod", jnp.mod, aliases=("_mod",))
_defbinary("broadcast_power", lambda a, b: jnp.power(a, b), aliases=("_power", "_Power", "pow"))
_defbinary("broadcast_maximum", jnp.maximum, aliases=("_maximum", "maximum"))
_defbinary("broadcast_minimum", jnp.minimum, aliases=("_minimum", "minimum"))
_defbinary("broadcast_hypot", jnp.hypot, aliases=("_hypot",))


def _cmp(fn):
    def impl(a, b):
        return fn(a, b).astype(jnp.result_type(a))
    return impl


_defbinary("broadcast_equal", _cmp(jnp.equal), aliases=("_equal",))
_defbinary("broadcast_not_equal", _cmp(jnp.not_equal), aliases=("_not_equal",))
_defbinary("broadcast_greater", _cmp(jnp.greater), aliases=("_greater",))
_defbinary("broadcast_greater_equal", _cmp(jnp.greater_equal), aliases=("_greater_equal",))
_defbinary("broadcast_lesser", _cmp(jnp.less), aliases=("_lesser",))
_defbinary("broadcast_lesser_equal", _cmp(jnp.less_equal), aliases=("_lesser_equal",))
_defbinary("broadcast_logical_and", _cmp(jnp.logical_and), aliases=("_logical_and",))
_defbinary("broadcast_logical_or", _cmp(jnp.logical_or), aliases=("_logical_or",))
_defbinary("broadcast_logical_xor", _cmp(jnp.logical_xor), aliases=("_logical_xor",))


# ----------------------------------------------------------------------
# scalar binary (reference: *_scalar ops with `scalar` attr)
# ----------------------------------------------------------------------
def _defscalar(name, fwd, rev=None, aliases=()):
    rev = rev or fwd

    def impl(data, *, scalar=1.0, reverse=False):
        s = jnp.asarray(scalar, dtype=data.dtype)
        return rev(s, data) if reverse else fwd(data, s)
    impl.__name__ = name
    register(name, aliases=aliases)(impl)


_defscalar("_plus_scalar", jnp.add, aliases=("_PlusScalar",))
_defscalar("_minus_scalar", jnp.subtract, jnp.subtract, aliases=("_MinusScalar",))
_defscalar("_mul_scalar", jnp.multiply, aliases=("_MulScalar",))
_defscalar("_div_scalar", _ref_div, _ref_div, aliases=("_DivScalar",))
_defscalar("_mod_scalar", jnp.mod, jnp.mod, aliases=("_ModScalar",))
_defscalar("_power_scalar", jnp.power, jnp.power, aliases=("_PowerScalar",))


def _defrscalar(name, fn, aliases=()):
    """Reversed scalar op: out = fn(scalar, data) — the reference's
    _r*_scalar ops (elemwise_binary_scalar_op_basic.cc) where the scalar
    is the LEFT operand."""
    def impl(data, *, scalar=1.0):
        s = jnp.asarray(scalar, dtype=data.dtype)
        return fn(s, data)
    impl.__name__ = name
    register(name, aliases=aliases)(impl)


_defrscalar("_rminus_scalar", jnp.subtract, aliases=("_RMinusScalar",))
_defrscalar("_rdiv_scalar", _ref_div, aliases=("_RDivScalar",))
_defrscalar("_rmod_scalar", jnp.mod, aliases=("_RModScalar",))
_defrscalar("_rpower_scalar", jnp.power, aliases=("_RPowerScalar",))
_defscalar("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_defscalar("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))


def _defscalar_cmp(name, fn):
    def impl(data, *, scalar=0.0, reverse=False):
        s = jnp.asarray(scalar, dtype=data.dtype)
        out = fn(s, data) if reverse else fn(data, s)
        return out.astype(data.dtype)
    impl.__name__ = name
    register(name)(impl)


_defscalar_cmp("_equal_scalar", jnp.equal)
_defscalar_cmp("_not_equal_scalar", jnp.not_equal)
_defscalar_cmp("_greater_scalar", jnp.greater)
_defscalar_cmp("_greater_equal_scalar", jnp.greater_equal)
_defscalar_cmp("_lesser_scalar", jnp.less)
_defscalar_cmp("_lesser_equal_scalar", jnp.less_equal)


# ----------------------------------------------------------------------
# unary math
# ----------------------------------------------------------------------
def _defunary(name, fn, aliases=()):
    def impl(data):
        return fn(data)
    impl.__name__ = name
    impl.__doc__ = "Elementwise %s (ref src/operator/tensor/elemwise_unary_op)" % name
    register(name, aliases=aliases)(impl)


_defunary("abs", jnp.abs, aliases=("_abs",))
_defunary("sign", jnp.sign)
_defunary("negative", jnp.negative)
_defunary("reciprocal", jnp.reciprocal)
_defunary("square", jnp.square)
_defunary("sqrt", jnp.sqrt)
_defunary("rsqrt", jax.lax.rsqrt)
_defunary("cbrt", jnp.cbrt)
_defunary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_defunary("exp", jnp.exp)
_defunary("log", jnp.log)
_defunary("log10", jnp.log10)
_defunary("log2", jnp.log2)
_defunary("log1p", jnp.log1p)
_defunary("expm1", jnp.expm1)
_defunary("sin", jnp.sin)
_defunary("cos", jnp.cos)
_defunary("tan", jnp.tan)
_defunary("arcsin", jnp.arcsin)
_defunary("arccos", jnp.arccos)
_defunary("arctan", jnp.arctan)
_defunary("sinh", jnp.sinh)
_defunary("cosh", jnp.cosh)
_defunary("tanh", jnp.tanh)
_defunary("arcsinh", jnp.arcsinh)
_defunary("arccosh", jnp.arccosh)
_defunary("arctanh", jnp.arctanh)
_defunary("degrees", jnp.degrees)
_defunary("radians", jnp.radians)
_defunary("floor", jnp.floor)
_defunary("ceil", jnp.ceil)
_defunary("trunc", jnp.trunc)
_defunary("rint", jnp.rint)
_defunary("round", jnp.round)
_defunary("fix", jnp.trunc)
_defunary("sigmoid", jax.nn.sigmoid)
_defunary("softsign", jax.nn.soft_sign)
_defunary("relu", jax.nn.relu)
_defunary("erf", jax.scipy.special.erf)
_defunary("erfinv", jax.scipy.special.erfinv)
_defunary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_defunary("gammaln", jax.scipy.special.gammaln)
_defunary("logical_not", lambda x: jnp.logical_not(x).astype(x.dtype))
_defunary("identity", lambda x: x, aliases=("_copy", "stop_gradient_off"))
# make_loss is registered below with its real gradient contract
_defunary("zeros_like", jnp.zeros_like)
_defunary("ones_like", jnp.ones_like)
_defunary("isnan", lambda x: jnp.isnan(x).astype("float32"))
_defunary("isinf", lambda x: jnp.isinf(x).astype("float32"))
_defunary("isfinite", lambda x: jnp.isfinite(x).astype("float32"))


@register("make_loss", aliases=("MakeLoss",))
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0,
              normalization="null"):
    """Loss head: identity forward; the backward seeds grad_scale into
    the graph regardless of head gradients (reference
    src/operator/make_loss.cc, incl. 'batch'/'valid' normalization)."""
    import jax

    @jax.custom_vjp
    def f(x):
        return x

    def f_fwd(x):
        return x, x

    def f_bwd(x, g):
        scale = jnp.asarray(grad_scale, jnp.float32)
        if normalization == "batch":
            scale = scale / x.shape[0]
        elif normalization == "valid":
            valid = jnp.maximum((jnp.abs(x) > valid_thresh)
                                .sum().astype(jnp.float32), 1.0)
            scale = scale / valid
        return (jnp.full(x.shape, 1.0, x.dtype) * scale.astype(x.dtype),)

    f.defvjp(f_fwd, f_bwd)
    return f(data)


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    """Stop gradient (ref src/operator/tensor/elemwise_unary_op_basic.cc)."""
    return jax.lax.stop_gradient(data)


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    """Sum of N arrays (ref src/operator/tensor/elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("clip")
def clip(data, *, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


@register("_scatter_set_nd")
def scatter_set_nd(lhs, rhs, indices, *, shape=None):
    return lhs.at[tuple(indices)].set(rhs)


# ----------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# ----------------------------------------------------------------------
def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _defreduce(name, fn, aliases=(), exclude_support=True):
    def impl(data, *, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            all_ax = set(range(data.ndim))
            sel = {a % data.ndim for a in (ax if isinstance(ax, tuple) else (ax,))}
            ax = tuple(sorted(all_ax - sel))
        return fn(data, axis=ax, keepdims=bool(keepdims))
    impl.__name__ = name
    register(name, aliases=aliases)(impl)


_defreduce("sum", jnp.sum, aliases=("sum_axis",))
_defreduce("mean", jnp.mean)
_defreduce("prod", jnp.prod)
_defreduce("max", jnp.max, aliases=("max_axis",))
_defreduce("min", jnp.min, aliases=("min_axis",))
_defreduce("nansum", jnp.nansum)
_defreduce("nanprod", jnp.nanprod)


@register("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims)))


@register("argmax")
def argmax(data, *, axis=None, keepdims=False):
    ax = None if axis is None else int(axis)
    out = jnp.argmax(data, axis=ax, keepdims=bool(keepdims))
    return out.astype("float32")


@register("argmin")
def argmin(data, *, axis=None, keepdims=False):
    ax = None if axis is None else int(axis)
    out = jnp.argmin(data, axis=ax, keepdims=bool(keepdims))
    return out.astype("float32")


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype("float32")


@register("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.log_softmax(x, axis=int(axis))


@register("softmax")
def softmax_op(data, *, axis=-1, temperature=None):
    """Softmax along axis (ref src/operator/nn/softmax.cc)."""
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.softmax(x, axis=int(axis))


@register("softmin")
def softmin(data, *, axis=-1, temperature=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.softmax(-x, axis=int(axis))


@register("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """Matrix product (ref src/operator/tensor/dot.cc). MXNet semantics:
    reduce over the last axis of lhs and the first axis of rhs."""
    a = lhs.T if transpose_a and lhs.ndim == 2 else lhs
    b = rhs.T if transpose_b and rhs.ndim == 2 else rhs
    if transpose_a and lhs.ndim > 2:
        a = jnp.moveaxis(lhs, 0, -1)
    if transpose_b and rhs.ndim > 2:
        b = jnp.moveaxis(rhs, -1, 0)
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("linalg_gemm2")
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    s2 = scalar * scalar
    absx = jnp.abs(data)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * jnp.square(data), absx - 0.5 / s2)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)
