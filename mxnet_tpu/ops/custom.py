"""The 'Custom' operator — dispatch into user CustomOpProp/CustomOp.

Reference parity: src/operator/custom/custom.cc (the C++ trampoline op
behind mx.nd.Custom / mx.sym.Custom). Here the trampoline is
``jax.pure_callback`` + ``jax.custom_vjp``: the user's Python
forward/backward run on host, embedded at the right point of the XLA
program, with shapes/dtypes declared up front from the prop's
infer_shape/infer_type so tracing (jit, eval_shape) never executes them.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from .registry import register, op_context


def _custom_num_outputs(attrs):
    from ..operator import _make_prop
    return len(_make_prop(attrs).list_outputs())


def _custom_kw_input_order(attrs):
    from ..operator import _make_prop
    prop = _make_prop(attrs)
    return prop.list_arguments() + prop.list_auxiliary_states()


def _set_custom_hooks():
    from .registry import get_op
    get_op("Custom").kw_input_order = _custom_kw_input_order


@register("Custom", num_outputs=_custom_num_outputs)
def _custom(*inputs, op_type=None, **prop_kwargs):
    """User-defined op: forwards to the CustomOpProp registered as
    ``op_type`` (reference operator.py register / custom.cc).

    Backend note: requires PJRT host callbacks (jax.pure_callback).
    Standard CPU/TPU runtimes support them; tunneled single-chip
    environments that disable host send/recv (e.g. axon) cannot run
    Custom ops on device — run them under the CPU platform there."""
    from ..operator import _make_prop
    from ..ndarray.ndarray import NDArray

    attrs = dict(prop_kwargs, op_type=op_type)
    prop = _make_prop(attrs)
    is_train = bool(op_context.is_train)

    # trailing inputs beyond list_arguments are auxiliary states
    # (reference custom.cc: arguments then aux states)
    n_args = len(prop.list_arguments())
    n_aux = len(inputs) - n_args
    if n_aux < 0:
        raise ValueError("Custom op '%s' expects %d arguments, got %d"
                         % (op_type, n_args, len(inputs)))

    in_shapes = [tuple(x.shape) for x in inputs[:n_args]]
    aux_shapes = [tuple(x.shape) for x in inputs[n_args:]]
    inferred = prop.infer_shape(list(in_shapes))
    out_shapes = [tuple(s) for s in inferred[1]]
    in_types = [_np.dtype(x.dtype) for x in inputs[:n_args]]
    aux_types = [_np.dtype(x.dtype) for x in inputs[n_args:]]
    out_types = [_np.dtype(t) for t in prop.infer_type(list(in_types))[1]]
    out_specs = tuple(jax.ShapeDtypeStruct(s, t)
                      for s, t in zip(out_shapes, out_types))
    in_specs = tuple(jax.ShapeDtypeStruct(s, t)
                     for s, t in zip(in_shapes, in_types))
    n_out = len(out_specs)
    n_in = n_args

    def _split(arrs):
        nds = [NDArray(jnp.asarray(a)) for a in arrs]
        return nds[:n_in], nds[n_in:]

    def _host_forward(*arrs):
        op = prop.create_operator(None, in_shapes, in_types)
        in_nd, aux_nd = _split(arrs)
        out_nd = [NDArray(jnp.zeros(s, t))
                  for s, t in zip(out_shapes, out_types)]
        op.forward(is_train, ["write"] * n_out, in_nd, out_nd, aux_nd)
        return tuple(_np.asarray(o._data, dtype=t)
                     for o, t in zip(out_nd, out_types))

    def _host_backward(*arrs):
        ograds = [NDArray(jnp.asarray(a)) for a in arrs[:n_out]]
        ins, aux_nd = _split(arrs[n_out:n_out + n_in + n_aux])
        outs = [NDArray(jnp.asarray(a))
                for a in arrs[n_out + n_in + n_aux:]]
        op = prop.create_operator(None, in_shapes, in_types)
        in_grad = [NDArray(jnp.zeros(s, t))
                   for s, t in zip(in_shapes, in_types)]
        op.backward(["write"] * n_in, ograds, ins, outs, in_grad, aux_nd)
        return tuple(_np.asarray(g._data, dtype=t)
                     for g, t in zip(in_grad, in_types))

    @jax.custom_vjp
    def f(*ins):
        out = jax.pure_callback(_host_forward, out_specs, *ins)
        return tuple(out)

    def f_fwd(*ins):
        outs = f(*ins)
        return outs, (ins, outs)

    def f_bwd(res, cts):
        ins, outs = res
        grads = jax.pure_callback(_host_backward, in_specs,
                                  *(tuple(cts) + tuple(ins) + tuple(outs)))
        # aux states receive zero cotangents (reference: aux is not
        # differentiated)
        aux_zeros = tuple(jnp.zeros(s, t)
                          for s, t in zip(aux_shapes, aux_types))
        return tuple(grads) + aux_zeros

    f.defvjp(f_fwd, f_bwd)
    outs = f(*inputs)
    return outs if len(outs) > 1 else outs[0]


_set_custom_hooks()
