"""Region-CNN contrib operators: Proposal / MultiProposal, PSROIPooling,
DeformableConvolution, DeformablePSROIPooling, and the deformable bits of
R-FCN.

Reference parity: src/operator/contrib/proposal.cc / multi_proposal.cc,
psroi_pooling-inl.h, nn/deformable_im2col.h + deformable_convolution-inl.h,
deformable_psroi_pooling-inl.h.

TPU-first notes
---------------
* The reference's hand-tiled CUDA kernels (deformable_im2col, per-bin
  atomic pooling) become vectorized bilinear gathers
  (``jax.scipy.ndimage.map_coordinates``) + one einsum on the MXU.
* Greedy NMS is a ``lax.fori_loop`` over a precomputed IoU matrix —
  static shapes, no host round-trips, same O(N²) work as the GPU kernel.
* PSROIPooling averages a fixed bilinear sample grid per bin (the
  deformable variant's ``sample_per_part`` semantics) instead of the
  integer-pixel enumeration of the non-deformable CUDA kernel; dynamic
  per-bin pixel counts would force data-dependent shapes under jit.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.ndimage import map_coordinates

from .registry import register


# ----------------------------------------------------------------------
# anchors + box transforms (proposal-inl.h helpers)
# ----------------------------------------------------------------------
def _generate_anchors(base_size, ratios, scales):
    """Reference generate_anchors: base box [0,0,base-1,base-1], ratio
    enumeration then scale enumeration. Returns (A, 4) float32 numpy."""
    base = np.array([0, 0, base_size - 1, base_size - 1], dtype=np.float64)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        size_r = size / r
        ws = np.round(np.sqrt(size_r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.asarray(anchors, dtype=np.float32)


def _bbox_pred(boxes, deltas):
    """Apply (dx, dy, dw, dh) deltas (proposal.cc BBoxTransformInv)."""
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(dw) * w
    ph = jnp.exp(dh) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                     axis=1)


def _iou_transform(boxes, deltas):
    """iou_loss=True variant: deltas move corners directly."""
    return jnp.stack([boxes[:, 0] + deltas[:, 0],
                      boxes[:, 1] + deltas[:, 1],
                      boxes[:, 2] + deltas[:, 2],
                      boxes[:, 3] + deltas[:, 3]], axis=1)


def _clip_boxes(boxes, height, width):
    return jnp.stack([jnp.clip(boxes[:, 0], 0, width - 1.0),
                      jnp.clip(boxes[:, 1], 0, height - 1.0),
                      jnp.clip(boxes[:, 2], 0, width - 1.0),
                      jnp.clip(boxes[:, 3], 0, height - 1.0)], axis=1)


def _greedy_nms_alive(boxes, order_scores, thresh):
    """Alive mask after greedy NMS on boxes pre-sorted by score desc.

    The IoU ROW for the current box is computed inside the loop body —
    O(N) live memory per step instead of materializing the full N×N IoU
    matrix (at the default rpn_pre_nms_top_n=6000 that matrix alone is
    144 MB/image before intermediates)."""
    n = boxes.shape[0]
    w = jnp.maximum(boxes[:, 2] - boxes[:, 0] + 1.0, 0.0)
    h = jnp.maximum(boxes[:, 3] - boxes[:, 1] + 1.0, 0.0)
    area = w * h
    valid = jnp.isfinite(order_scores)
    idx = jnp.arange(n)

    def body(i, alive):
        bi = boxes[i]
        ix1 = jnp.maximum(bi[0], boxes[:, 0])
        iy1 = jnp.maximum(bi[1], boxes[:, 1])
        ix2 = jnp.minimum(bi[2], boxes[:, 2])
        iy2 = jnp.minimum(bi[3], boxes[:, 3])
        inter = (jnp.maximum(ix2 - ix1 + 1.0, 0.0)
                 * jnp.maximum(iy2 - iy1 + 1.0, 0.0))
        iou_row = inter / jnp.maximum(area[i] + area - inter, 1e-12)
        sup = ((idx < i) & alive & (iou_row > thresh)).any()
        return alive.at[i].set(valid[i] & ~sup)

    return lax.fori_loop(0, n, body, jnp.zeros((n,), bool))


def _proposal_impl(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride, iou_loss):
    """Per-image proposal generation. cls_prob (2A, H, W),
    bbox_pred (4A, H, W), im_info (3,) = [height, width, scale]."""
    A2, H, W = cls_prob.shape
    A = A2 // 2
    base_np = _generate_anchors(feature_stride, ratios, scales)
    if base_np.shape[0] != A:
        raise ValueError(
            "Proposal: cls_prob has %d anchor channels but scales×ratios "
            "give %d anchors" % (A, base_np.shape[0]))
    base = jnp.asarray(base_np)
    # grid of shifts, (A, H, W, 4) flattened in (A, H, W) order — the
    # reference's workspace order (proposal.cc: index = a*H*W + h*W + w)
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shift = jnp.stack([
        jnp.broadcast_to(sx[None, :], (H, W)),
        jnp.broadcast_to(sy[:, None], (H, W)),
        jnp.broadcast_to(sx[None, :], (H, W)),
        jnp.broadcast_to(sy[:, None], (H, W))], axis=-1)  # (H, W, 4)
    anchors = (base[:, None, None, :] + shift[None]).reshape(-1, 4)

    scores = cls_prob[A:].reshape(-1)                       # (A*H*W,)
    deltas = bbox_pred.reshape(A, 4, H, W).transpose(0, 2, 3, 1)
    deltas = deltas.reshape(-1, 4)

    height, width, scale = im_info[0], im_info[1], im_info[2]
    boxes = (_iou_transform if iou_loss else _bbox_pred)(anchors, deltas)
    boxes = _clip_boxes(boxes, height, width)

    min_size = rpn_min_size * scale
    bw = boxes[:, 2] - boxes[:, 0] + 1.0
    bh = boxes[:, 3] - boxes[:, 1] + 1.0
    scores = jnp.where((bw >= min_size) & (bh >= min_size), scores,
                       -jnp.inf)

    pre_n = min(int(rpn_pre_nms_top_n), scores.shape[0]) \
        if rpn_pre_nms_top_n > 0 else scores.shape[0]
    top_scores, top_idx = lax.top_k(scores, pre_n)
    top_boxes = boxes[top_idx]

    alive = _greedy_nms_alive(top_boxes, top_scores, threshold)
    # first post_n alive entries, in score order; pad by recycling the
    # best surviving box (reference pads its fixed-size output the same
    # way — proposal.cc copies from the kept set cyclically)
    post_n = int(rpn_post_nms_top_n)
    rank = jnp.where(alive, jnp.arange(pre_n), pre_n + jnp.arange(pre_n))
    pick = jnp.argsort(rank)[:post_n]
    # pick has min(pre_n, post_n) entries; each remapped index below is
    # either i < min(n_alive, post_n) or i % n_alive, both < len(pick),
    # so the gather stays in bounds even when pre_n < post_n
    n_alive = alive.sum()
    pick = pick[jnp.where(jnp.arange(post_n) < n_alive,
                          jnp.arange(post_n),
                          jnp.arange(post_n) % jnp.maximum(n_alive, 1))]
    return top_boxes[pick], top_scores[pick]


@register("_contrib_Proposal", aliases=("Proposal",),
          num_outputs=lambda attrs: 2 if attrs.get("output_score") else 1)
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal layer (ref src/operator/contrib/proposal.cc).
    Returns rois (B*post_nms_top_n, 5) rows [batch_idx, x1, y1, x2, y2]
    (+ scores (B*post_nms_top_n, 1) when output_score)."""
    B = cls_prob.shape[0]

    def one(cp, bp, info):
        return _proposal_impl(
            cp, bp, info, rpn_pre_nms_top_n=rpn_pre_nms_top_n,
            rpn_post_nms_top_n=rpn_post_nms_top_n, threshold=threshold,
            rpn_min_size=rpn_min_size, scales=tuple(scales),
            ratios=tuple(ratios), feature_stride=feature_stride,
            iou_loss=iou_loss)

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    post_n = boxes.shape[1]
    bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), post_n)
    rois = jnp.concatenate([bidx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("_contrib_MultiProposal", aliases=("MultiProposal",),
          num_outputs=lambda attrs: 2 if attrs.get("output_score") else 1)
def multi_proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   feature_stride=16, output_score=False, iou_loss=False):
    """Batched Proposal (ref multi_proposal.cc — identical math, batch
    handled in one launch; our Proposal is already batched, so this is
    the same computation)."""
    return proposal(cls_prob, bbox_pred, im_info,
                    rpn_pre_nms_top_n=rpn_pre_nms_top_n,
                    rpn_post_nms_top_n=rpn_post_nms_top_n,
                    threshold=threshold, rpn_min_size=rpn_min_size,
                    scales=scales, ratios=ratios,
                    feature_stride=feature_stride,
                    output_score=output_score, iou_loss=iou_loss)


# ----------------------------------------------------------------------
# position-sensitive ROI pooling (psroi_pooling-inl.h)
# ----------------------------------------------------------------------
def _ps_pool(data, rois, trans, *, spatial_scale, output_dim, group_size,
             pooled_size, part_size, sample_per_part, trans_std):
    """Shared position-sensitive pooling core: output channel c, bin
    (i, j) averages an s×s bilinear sample grid of input channel
    c*G² + gi*G + gj ONLY (no wasted gathers on unmapped channels),
    with bins optionally shifted by normalized ``trans`` offsets."""
    G = int(group_size) if group_size else int(pooled_size)
    P = int(pooled_size)
    PT = int(part_size) if part_size else P
    s = max(int(sample_per_part), 1)
    C_out = int(output_dim)
    use_trans = trans is not None
    if use_trans:
        n_cls = trans.shape[1] // 2
        cls_each = max(C_out // n_cls, 1)

    gi = jnp.minimum((jnp.arange(P) * G) // P, G - 1)
    chan = (jnp.arange(C_out)[:, None, None] * G * G
            + gi[None, :, None] * G + gi[None, None, :])      # (C,P,P)
    pi = jnp.minimum((jnp.arange(P) * PT) // P, PT - 1)
    frac = (jnp.arange(s) + 0.5) / s

    def one(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - 0.5
        y1 = roi[2] * spatial_scale - 0.5
        x2 = (roi[3] + 1.0) * spatial_scale - 0.5
        y2 = (roi[4] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / P, rh / P
        img = data[b]
        if use_trans:
            cls = jnp.minimum(jnp.arange(C_out) // cls_each, n_cls - 1)
            # channel 2*cls is trans_x, 2*cls+1 is trans_y
            # (deformable_psroi_pooling.cu:118-124)
            dx = tr[2 * cls][:, pi][:, :, pi] * trans_std * rw   # (C,P,P)
            dy = tr[2 * cls + 1][:, pi][:, :, pi] * trans_std * rh
        else:
            dy = jnp.zeros((C_out, P, P))
            dx = jnp.zeros((C_out, P, P))
        # sample coords per (c, i, j, a, b): bin (i, j)'s s×s grid + shift
        ys = y1 + (jnp.arange(P)[:, None] + frac[None, :]) * bin_h  # (P,s)
        xs = x1 + (jnp.arange(P)[:, None] + frac[None, :]) * bin_w
        Y = ys[None, :, None, :, None] + dy[:, :, :, None, None]
        X = xs[None, None, :, None, :] + dx[:, :, :, None, None]
        Y = jnp.broadcast_to(Y, (C_out, P, P, s, s)).reshape(-1, s * s)
        X = jnp.broadcast_to(X, (C_out, P, P, s, s)).reshape(-1, s * s)
        planes = img[chan.reshape(-1)]                        # (C*P*P,H,W)
        vals = jax.vmap(lambda pl, y, x: map_coordinates(
            pl, [y, x], order=1, mode="constant", cval=0.0))(planes, Y, X)
        return vals.mean(axis=1).reshape(C_out, P, P)

    if use_trans:
        return jax.vmap(one)(rois, trans)
    return jax.vmap(one, in_axes=(0, None))(rois, None)


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, *, spatial_scale, output_dim, pooled_size,
                  group_size=0, sample_per_part=2):
    """Position-sensitive ROI pooling (ref psroi_pooling-inl.h): output
    channel c, bin (i, j) pools input channel c*G² + i*G + j over bin
    (i, j) of the ROI. Bins average a sample_per_part² bilinear grid
    (the deformable variant's sampling; the CUDA kernel enumerates
    integer pixels, which is shape-dynamic and jit-hostile)."""
    return _ps_pool(data, rois, None, spatial_scale=spatial_scale,
                    output_dim=output_dim, group_size=group_size,
                    pooled_size=pooled_size, part_size=0,
                    sample_per_part=sample_per_part, trans_std=0.0)


# ----------------------------------------------------------------------
# deformable convolution (nn/deformable_im2col.h)
# ----------------------------------------------------------------------
@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, *, kernel,
                           num_filter, stride=(1, 1), dilate=(1, 1),
                           pad=(0, 0), num_group=1,
                           num_deformable_group=1, workspace=1024,
                           no_bias=False, layout=None):
    """Deformable convolution v1 (ref deformable_convolution-inl.h):
    each kernel tap samples the input at a learned fractional offset.
    The CUDA deformable_im2col becomes a batched bilinear gather; the
    contraction runs as one einsum on the MXU."""
    B, C, H, W = data.shape
    KH, KW = int(kernel[0]), int(kernel[1])
    SH, SW = int(stride[0]), int(stride[1])
    DH, DW = int(dilate[0]), int(dilate[1])
    PH, PW = int(pad[0]), int(pad[1])
    DG = int(num_deformable_group)
    G = int(num_group)
    F = int(num_filter)
    Ho = (H + 2 * PH - DH * (KH - 1) - 1) // SH + 1
    Wo = (W + 2 * PW - DW * (KW - 1) - 1) // SW + 1
    K = KH * KW

    # base sampling grid per kernel tap: (K, Ho, Wo)
    base_y = (jnp.arange(Ho) * SH - PH)[None, :, None] + \
        (jnp.arange(KH).repeat(KW) * DH)[:, None, None]
    base_x = (jnp.arange(Wo) * SW - PW)[None, None, :] + \
        (jnp.tile(jnp.arange(KW), KH) * DW)[:, None, None]
    base_y = jnp.broadcast_to(base_y, (K, Ho, Wo)).astype(jnp.float32)
    base_x = jnp.broadcast_to(base_x, (K, Ho, Wo)).astype(jnp.float32)

    # offset channels: [dg, k, (y, x)] (deformable_im2col.h layout)
    offs = offset.reshape(B, DG, K, 2, Ho, Wo)

    def sample_image(img, off):           # (C,H,W), (DG,K,2,Ho,Wo)
        ys = base_y[None] + off[:, :, 0]  # (DG, K, Ho, Wo)
        xs = base_x[None] + off[:, :, 1]
        img_g = img.reshape(DG, C // DG, H, W)

        def per_dg(chans, y, x):          # (C/DG,H,W), (K,Ho,Wo)
            def per_chan(ch):
                return jax.vmap(lambda yy, xx: map_coordinates(
                    ch, [yy, xx], order=1, mode="constant", cval=0.0))(y, x)
            return jax.vmap(per_chan)(chans)   # (C/DG, K, Ho, Wo)

        cols = jax.vmap(per_dg)(img_g, ys, xs)  # (DG, C/DG, K, Ho, Wo)
        return cols.reshape(C, K, Ho, Wo)

    cols = jax.vmap(sample_image)(data, offs)   # (B, C, K, Ho, Wo)
    w = weight.reshape(G, F // G, C // G, K)
    colg = cols.reshape(B, G, C // G, K, Ho, Wo)
    out = jnp.einsum("gfck,bgckhw->bgfhw", w, colg,
                     preferred_element_type=jnp.float32)
    out = out.reshape(B, F, Ho, Wo).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, F, 1, 1)
    return out


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans=None, *, spatial_scale,
                             output_dim, group_size, pooled_size,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling
    (ref deformable_psroi_pooling-inl.h): PSROIPooling whose bins are
    shifted by learned normalized offsets from ``trans``."""
    use_trans = (trans is not None) and not no_trans
    return _ps_pool(data, rois, trans if use_trans else None,
                    spatial_scale=spatial_scale, output_dim=output_dim,
                    group_size=group_size, pooled_size=pooled_size,
                    part_size=part_size, sample_per_part=sample_per_part,
                    trans_std=trans_std)
