"""Backward shape-inference rules for parameterized ops.

The reference implements full bidirectional shape inference per op
(FInferShape, e.g. src/operator/nn/fully_connected.cc:55-95) so that
``simple_bind`` can size weights from data shapes alone. On TPU the forward
direction is free (``jax.eval_shape``); only the backward direction —
"given data shape + attrs, what are the parameter shapes" — needs rules,
and only for ops that own parameters. Also declares which optional inputs
are absent for given attrs (nnvm's FListInputNames dependence on params).
"""
from __future__ import annotations

import numpy as _np

from .registry import get_op
from .rnn import rnn_param_size


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _set(opname, param_shapes=None, unused_inputs=None):
    op = get_op(opname)
    if param_shapes is not None:
        op.param_shapes = param_shapes
    if unused_inputs is not None:
        op.unused_inputs = unused_inputs


def _fc_shapes(known, attrs):
    out = {}
    data = known.get("data")
    nh = int(attrs["num_hidden"])
    if data is not None:
        in_dim = _prod(data[1:]) if attrs.get("flatten", True) else data[-1]
        out["weight"] = (nh, in_dim)
    out["bias"] = (nh,)
    return out


_set("FullyConnected", _fc_shapes,
     lambda attrs: {"bias"} if attrs.get("no_bias") else set())


def _conv_shapes(known, attrs):
    out = {}
    data = known.get("data")
    nf = int(attrs["num_filter"])
    kernel = tuple(int(k) for k in attrs["kernel"])
    g = int(attrs.get("num_group", 1))
    layout = attrs.get("layout")
    if data is not None:
        if layout and str(layout).endswith("C"):
            # channel-last data pairs with channel-last weights
            out["weight"] = (nf,) + kernel + (data[-1] // g,)
        else:
            out["weight"] = (nf, data[1] // g) + kernel
    out["bias"] = (nf,)
    return out


_set("Convolution", _conv_shapes,
     lambda attrs: {"bias"} if attrs.get("no_bias") else set())


def _deconv_shapes(known, attrs):
    out = {}
    data = known.get("data")
    nf = int(attrs["num_filter"])
    kernel = tuple(int(k) for k in attrs["kernel"])
    g = int(attrs.get("num_group", 1))
    if data is not None:
        out["weight"] = (data[1], nf // g) + kernel
    out["bias"] = (nf,)
    return out


_set("Deconvolution", _deconv_shapes,
     lambda attrs: {"bias"} if attrs.get("no_bias", True) else set())


def _channel_shapes(known, attrs):
    data = known.get("data")
    if data is None:
        return {}
    ax = int(attrs.get("axis", 1)) % len(data)
    c = (data[ax],)
    return {"gamma": c, "beta": c, "moving_mean": c, "moving_var": c}


_set("BatchNorm", _channel_shapes)
_set("_contrib_SyncBatchNorm", _channel_shapes)


def _switch_moe_shapes(known, attrs):
    data = known.get("data")
    if data is None:
        return {}
    d = data[-1]
    E = int(attrs["num_experts"])
    h = int(attrs["num_hidden"])
    return {"router_weight": (d, E),
            "expert_up_weight": (E, d, h), "expert_up_bias": (E, h),
            "expert_down_weight": (E, h, d), "expert_down_bias": (E, d)}


_set("_contrib_SwitchMoE", _switch_moe_shapes)


def _fused_attn_shapes(known, attrs):
    data = known.get("data")
    if data is None:
        return {}
    d = int(data[-1])
    return {"qkv_weight": (3 * d, d), "qkv_bias": (3 * d,),
            "proj_weight": (d, d), "proj_bias": (d,)}


_set("_contrib_FusedCausalSelfAttention", _fused_attn_shapes)
# the paged decode/prefill ops share the fused op's projection-weight
# layout; cache/table/position shapes come from bind-time inputs or
# explicit Variable shapes, never from inference
_set("_contrib_PagedDecodeAttention", _fused_attn_shapes)
_set("_contrib_PagedPrefillAttention", _fused_attn_shapes)
_set("_contrib_PagedChunkPrefillAttention", _fused_attn_shapes)


def _ln_shapes(known, attrs):
    data = known.get("data")
    if data is None:
        return {}
    ax = int(attrs.get("axis", -1)) % len(data)
    return {"gamma": (data[ax],), "beta": (data[ax],)}


_set("LayerNorm", _ln_shapes)
_set("InstanceNorm", lambda known, attrs: (
    {"gamma": (known["data"][1],), "beta": (known["data"][1],)}
    if known.get("data") is not None else {}))


_set("Embedding", lambda known, attrs: {
    "weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))})
_set("_contrib_ShardedEmbedding", lambda known, attrs: {
    "weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))})


def _leaky_shapes(known, attrs):
    data = known.get("data")
    if attrs.get("act_type") == "prelu" and data is not None:
        return {"gamma": (data[1],)}
    return {}


_set("LeakyReLU", _leaky_shapes,
     lambda attrs: set() if attrs.get("act_type") == "prelu" else {"gamma"})


def _rnn_shapes(known, attrs):
    data = known.get("data")
    out = {}
    mode = attrs.get("mode", "lstm")
    L = int(attrs["num_layers"])
    H = int(attrs["state_size"])
    bi = bool(attrs.get("bidirectional", False))
    ndir = 2 if bi else 1
    if data is not None:
        out["parameters"] = (rnn_param_size(L, int(data[2]), H, bi, mode),)
        out["state"] = (L * ndir, int(data[1]), H)
        if mode == "lstm":
            out["state_cell"] = (L * ndir, int(data[1]), H)
    return out


_set("RNN", _rnn_shapes,
     lambda attrs: set() if attrs.get("mode", "lstm") == "lstm" else {"state_cell"})

def _softmax_output_shapes(known, attrs):
    d = known.get("data")
    if d is None:
        return {}
    if attrs.get("multi_output"):
        return {"label": (d[0],) + tuple(d[2:])}
    return {"label": tuple(d[:-1])}


_set("SoftmaxOutput", _softmax_output_shapes)
for _nm in ("LinearRegressionOutput", "MAERegressionOutput",
            "LogisticRegressionOutput"):
    _set(_nm, lambda known, attrs: (
        {"label": known["data"]} if known.get("data") is not None else {}))

_set("SequenceMask",
     unused_inputs=lambda attrs: set() if attrs.get("use_sequence_length") else {"sequence_length"})
_set("SequenceLast",
     unused_inputs=lambda attrs: set() if attrs.get("use_sequence_length") else {"sequence_length"})
_set("SequenceReverse",
     unused_inputs=lambda attrs: set() if attrs.get("use_sequence_length") else {"sequence_length"})


# same weight/bias shapes as Convolution (offset is a data input)
_set("_contrib_DeformableConvolution", _conv_shapes,
     lambda attrs: {"bias"} if attrs.get("no_bias") else set())
_set("_contrib_DeformablePSROIPooling",
     unused_inputs=lambda attrs: {"trans"} if attrs.get("no_trans") else set())
