"""Fused optimizer update operators.

Reference parity: src/operator/optimizer_op.cc (sgd_update, sgd_mom_update,
adam_update, signsgd_update, signum_update, ftrl_update, rmsprop_update,
mp_sgd_* multi-precision variants). Each is one fused XLA computation; state
tensors (mom, mean, var) are declared as mutated inputs so the eager path
updates them in place like the reference's FMutateInputs.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _apply_common(grad, weight, rescale_grad, clip_gradient, wd=0.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd:
        g = g + wd * weight.astype(jnp.float32)
    return g


@register("sgd_update")
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("mom", 1),))
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    new_mom = momentum * mom.astype(jnp.float32) - lr * g
    new_w = weight.astype(jnp.float32) + new_mom
    return new_w.astype(weight.dtype), new_mom.astype(mom.dtype)


@register("mp_sgd_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("weight32", 1),))
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: fp32 master weights, low-precision model weights
    (ref src/operator/optimizer_op.cc MP_SGD)."""
    g = _apply_common(grad, weight32, rescale_grad, clip_gradient, wd)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(("mom", 1), ("weight32", 2)))
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _apply_common(grad, weight32, rescale_grad, clip_gradient, wd)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(("mean", 1), ("var", 2)))
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = (weight.astype(jnp.float32)
             - lr * new_mean / (jnp.sqrt(new_var) + epsilon))
    return new_w.astype(weight.dtype), new_mean, new_var


@register("signsgd_update")
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, 0.0)
    return (weight.astype(jnp.float32)
            - lr * (jnp.sign(g) + wd * weight)).astype(weight.dtype)


@register("signum_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("mom", 1),))
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """Signum: momentum SGD taking the sign of the momentum
    (rahul003's Signum optimizer; ref src/operator/optimizer_op.cc)."""
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (weight.astype(jnp.float32)
             + lr * (jnp.sign(new_mom) - wd_lh * weight))
    return new_w.astype(weight.dtype), new_mom


@register("rmsprop_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("n", 1),))
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight.astype(jnp.float32) - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n


@register("rmspropalex_update", num_outputs=4, num_visible_outputs=1,
          mutate_inputs=(("n", 1), ("g", 2), ("delta", 3)))
def rmspropalex_update(weight, grad, n, g, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _apply_common(grad, weight, rescale_grad, clip_gradient, wd)
    new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1 - gamma1) * gr + gamma1 * g
    new_delta = (gamma2 * delta
                 - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon))
    new_w = weight.astype(jnp.float32) + new_delta
    return new_w.astype(weight.dtype), new_n, new_g, new_delta


@register("ftrl_update", num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(("z", 1), ("n", 2)))
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, 0.0)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w.astype(weight.dtype), new_z, new_n


@register("adagrad_update", num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(("history", 1),))
def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_common(grad, weight, rescale_grad, clip_gradient, 0.0)
    new_h = history + jnp.square(g)
    new_w = (weight.astype(jnp.float32)
             - lr * (g / jnp.sqrt(new_h + epsilon) + wd * weight))
    return new_w.astype(weight.dtype), new_h
