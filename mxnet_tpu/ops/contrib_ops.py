"""Contrib operators: SSD detection ops + CTC loss.

Reference parity: src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc (the op trio behind the SSD
example, BASELINE config 5), bounding_box.cc (box_nms), and
ctc_loss.cc. TPU-native: everything is fixed-shape jnp — matching is
argmax/where over the full anchor×object matrix (no data-dependent
loops), NMS is the O(k²) suppression matrix over the top-k boxes
(compiler-friendly, no dynamic shapes), and CTC is the standard
log-alpha recursion as one ``lax.scan`` over time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_NEG_INF = -1e30


# ----------------------------------------------------------------------
# MultiBoxPrior
# ----------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate SSD anchor boxes for every feature-map cell (reference
    multibox_prior.cc). Output (1, H*W*num_anchors, 4) corners in
    normalized coords; num_anchors = len(sizes) + len(ratios) - 1."""
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in (sizes if isinstance(sizes, (tuple, list))
                                     else (sizes,)))
    ratios = tuple(float(r) for r in
                   (ratios if isinstance(ratios, (tuple, list))
                    else (ratios,)))
    step_y = 1.0 / h if steps[0] <= 0 else float(steps[0])
    step_x = 1.0 / w if steps[1] <= 0 else float(steps[1])
    cy = (jnp.arange(h, dtype=jnp.float32) + float(offsets[0])) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + float(offsets[1])) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")  # (h, w)

    # anchor set: (size_i, ratio_0) for all i + (size_0, ratio_j) j>0
    half_wh = []
    for s in sizes:
        r = ratios[0]
        half_wh.append((s * (r ** 0.5) / 2.0, s / (r ** 0.5) / 2.0))
    for r in ratios[1:]:
        s = sizes[0]
        half_wh.append((s * (r ** 0.5) / 2.0, s / (r ** 0.5) / 2.0))
    hw = jnp.asarray(half_wh, dtype=jnp.float32)  # (A, 2): (hw_x, hw_y)

    cxe = cx[:, :, None]
    cye = cy[:, :, None]
    xmin = cxe - hw[None, None, :, 0]
    ymin = cye - hw[None, None, :, 1]
    xmax = cxe + hw[None, None, :, 0]
    ymax = cye + hw[None, None, :, 1]
    out = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)  # (h, w, A, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out.reshape(1, -1, 4)


def _iou_matrix(anchors, gts):
    """IoU between anchors (A,4) and gt boxes (M,4), corner format."""
    ax1, ay1, ax2, ay2 = [anchors[:, i] for i in range(4)]
    gx1, gy1, gx2, gy2 = [gts[:, i] for i in range(4)]
    ix1 = jnp.maximum(ax1[:, None], gx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], gy1[None, :])
    ix2 = jnp.minimum(ax2[:, None], gx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], gy2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    a_area = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    g_area = jnp.maximum((gx2 - gx1) * (gy2 - gy1), 0.0)
    union = a_area[:, None] + g_area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          num_outputs=3)
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to ground truth and encode regression targets
    (reference multibox_target.cc). label: (B, M, 5) rows
    [cls, xmin, ymin, xmax, ymax], cls = -1 pads. Returns
    (loc_target (B, A*4), loc_mask (B, A*4), cls_target (B, A))."""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    v = jnp.asarray(variances, jnp.float32)

    def one(lab, cls_pred_one):
        gt_cls = lab[:, 0]
        gt_boxes = lab[:, 1:5]
        valid = gt_cls >= 0  # (M,)
        iou = _iou_matrix(anchors, gt_boxes)  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # stage 1: each valid gt claims its best anchor (pad rows scatter
        # out of bounds and are dropped — they must not clobber claims)
        best_anchor_per_gt = jnp.argmax(iou, axis=0)          # (M,)
        scatter_idx = jnp.where(valid, best_anchor_per_gt, A)
        # stage 2: anchors claim their best gt if above threshold
        best_gt = jnp.argmax(iou, axis=1)                     # (A,)
        best_iou = jnp.max(iou, axis=1)                       # (A,)
        matched_gt = jnp.where(best_iou > overlap_threshold, best_gt, -1)
        # gt-claimed anchors override
        claimed = jnp.full((A,), -1, jnp.int32)
        claimed = claimed.at[scatter_idx].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32), mode="drop")
        matched = jnp.where(claimed >= 0, claimed, matched_gt)  # (A,)

        is_pos = matched >= 0
        mg = jnp.clip(matched, 0, lab.shape[0] - 1)
        cls_t = jnp.where(is_pos, gt_cls[mg] + 1.0, 0.0)

        # hard-negative mining (reference multibox_target.cc NegativeMining):
        # rank unmatched anchors by predicted non-background confidence,
        # keep the hardest ratio*num_pos (>= minimum_negative_samples) as
        # class-0 negatives, mark the rest ignore_label
        if negative_mining_ratio > 0:
            # eligibility: true negatives are anchors whose best IoU stays
            # below negative_mining_thresh (reference multibox_target.cc);
            # ranking is by predicted non-background confidence, so the
            # requested count is always met when enough anchors exist
            p = jax.nn.softmax(cls_pred_one, axis=0)  # (C, A)
            neg_conf = 1.0 - p[0]
            eligible = (~is_pos) & (best_iou < negative_mining_thresh)
            score = jnp.where(eligible, neg_conf, -jnp.inf)
            num_pos = is_pos.sum()
            k = jnp.maximum(num_pos * negative_mining_ratio,
                            minimum_negative_samples)
            rank = jnp.argsort(jnp.argsort(-score))
            is_neg = eligible & (rank < k)
            cls_t = jnp.where(is_pos | is_neg, cls_t, float(ignore_label))

        # encode offsets (SSD parameterization)
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
        ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
        g = gt_boxes[mg]
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        tx = (gcx - acx) / aw / v[0]
        ty = (gcy - acy) / ah / v[1]
        tw = jnp.log(gw / aw) / v[2]
        th = jnp.log(gh / ah) / v[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=1)  # (A, 4)
        loc_t = jnp.where(is_pos[:, None], loc_t, 0.0)
        loc_m = jnp.where(is_pos[:, None],
                          jnp.ones((A, 4), jnp.float32), 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",))
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions into detections with per-class NMS (reference
    multibox_detection.cc). cls_prob (B, C, A), loc_pred (B, A*4),
    anchor (1, A, 4) → (B, A, 6) rows [cls_id, score, x1, y1, x2, y2],
    cls_id = -1 for suppressed/background."""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    v = jnp.asarray(variances, jnp.float32)

    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def one(probs, locs):
        # decode boxes
        l = locs.reshape(A, 4)
        cx = l[:, 0] * v[0] * aw + acx
        cy = l[:, 1] * v[1] * ah + acy
        w = jnp.exp(l[:, 2] * v[2]) * aw / 2
        h = jnp.exp(l[:, 3] * v[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor; reported ids are 0-based
        # object classes (channel index minus the background slot,
        # reference multibox_detection.cc class_id = j - 1)
        pr = probs.T  # (A, C)
        masked = pr.at[:, background_id].set(-1.0)
        chan = jnp.argmax(masked, axis=1)
        score = jnp.max(masked, axis=1)
        cls_id = chan - (chan > background_id).astype(chan.dtype)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1)
        score = jnp.where(keep, score, 0.0)

        # NMS: suppression by any higher-scored overlapping box of the
        # same class (or any class when force_suppress). Only the static
        # top-k ranks enter the k×k suppression matrix — boxes beyond
        # nms_topk are dropped outright (reference nms_topk semantics),
        # keeping the matrix O(k²) for SSD-scale anchor counts.
        k = min(int(nms_topk), A) if nms_topk > 0 else A
        order = jnp.argsort(-score)[:k]
        b_s = boxes[order]
        s_s = score[order]
        c_s = cls_id[order]
        iou = _iou_matrix(b_s, b_s)
        higher = jnp.tril(jnp.ones((k, k), bool), k=-1)  # j < i: higher score
        same_cls = (c_s[:, None] == c_s[None, :]) if not force_suppress \
            else jnp.ones((k, k), bool)

        def nms_body(i, alive):
            sup = (higher[i] & same_cls[i] & (c_s >= 0) & alive
                   & (iou[i] > nms_threshold)).any()
            keep_i = (c_s[i] >= 0) & ~sup
            return alive.at[i].set(keep_i)

        alive = jax.lax.fori_loop(0, k, nms_body, jnp.zeros((k,), bool))
        out_cls = jnp.where(alive, c_s.astype(jnp.float32), -1.0)
        out = jnp.concatenate([out_cls[:, None], s_s[:, None], b_s], axis=1)
        if k < A:
            pad = jnp.concatenate(
                [jnp.full((A - k, 1), -1.0),
                 jnp.zeros((A - k, 5), jnp.float32)], axis=1)
            out = jnp.concatenate([out, pad], axis=0)
        return out

    return jax.vmap(one)(cls_prob, loc_pred)


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner", background_id=-1):
    """Generic NMS over (..., N, K) box tensors (reference
    bounding_box.cc box_nms). Suppressed rows get score -1."""
    shape = data.shape
    flat = data.reshape(-1, shape[-2], shape[-1])
    N = shape[-2]
    cs = int(coord_start)

    def one(rows):
        score = rows[:, score_index]
        boxes = rows[:, cs:cs + 4]
        if in_format == "center":
            cx, cy, w, h = (boxes[:, 0], boxes[:, 1], boxes[:, 2],
                            boxes[:, 3])
            boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                               cy + h / 2], axis=1)
        valid = score > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= rows[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
        r_s = rows[order]
        b_s = boxes[order]
        s_v = valid[order]
        if topk > 0:
            s_v &= jnp.arange(N) < topk
        iou = _iou_matrix(b_s, b_s)
        higher = jnp.tril(jnp.ones((N, N), bool), k=-1)
        if id_index >= 0 and not force_suppress:
            ids = r_s[:, id_index]
            same = ids[:, None] == ids[None, :]
        else:
            same = jnp.ones((N, N), bool)

        def body(i, alive):
            sup = (higher[i] & same[i] & alive
                   & (iou[i] > overlap_thresh)).any()
            return alive.at[i].set(s_v[i] & ~sup)

        alive = jax.lax.fori_loop(0, N, body, jnp.zeros((N,), bool))
        out = r_s.at[:, score_index].set(
            jnp.where(alive, r_s[:, score_index], -1.0))
        if out_format != in_format:
            if out_format == "corner":  # center -> corner (b_s already is)
                out = out.at[:, cs:cs + 4].set(b_s)
            else:  # corner -> center
                x1, y1, x2, y2 = (b_s[:, 0], b_s[:, 1], b_s[:, 2], b_s[:, 3])
                ctr = jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2,
                                 x2 - x1, y2 - y1], axis=1)
                out = out.at[:, cs:cs + 4].set(ctr)
        return out

    out = jax.vmap(one)(flat)
    return out.reshape(shape)


# ----------------------------------------------------------------------
# CTC loss
# ----------------------------------------------------------------------
@register("_contrib_ctc_loss", aliases=("ctc_loss", "CTCLoss"))
def ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist temporal classification loss (reference
    ctc_loss.cc / contrib.ctc_loss). data: (T, B, C) unnormalized
    activations; label: (B, L) class indices (0-padded when
    blank_label='first', in which case classes are 1-based like the
    reference). Returns per-example negative log likelihood (B,).
    Implemented as the log-alpha recursion in one lax.scan — the
    XLA-native CTC (no cuDNN/warpctc analog needed)."""
    T, B, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=2)

    if blank_label == "first":
        blank = 0
        lab = label.astype(jnp.int32)  # labels are 1..C-1, 0 = pad
        lab_valid = lab > 0
    else:
        blank = C - 1
        lab = label.astype(jnp.int32)
        lab_valid = lab >= 0
        lab = jnp.where(lab_valid, lab, 0)

    if use_label_lengths and label_lengths is not None:
        lens = label_lengths.astype(jnp.int32)
    else:
        lens = lab_valid.sum(axis=1).astype(jnp.int32)
    if use_data_lengths and data_lengths is not None:
        t_lens = data_lengths.astype(jnp.int32)
    else:
        t_lens = jnp.full((B,), T, jnp.int32)

    # extended label sequence: blank l1 blank l2 ... lL blank (len 2L+1)
    S = 2 * L + 1
    pos = jnp.arange(S)
    lab_idx = jnp.clip((pos - 1) // 2, 0, L - 1)
    ext = jnp.where(pos % 2 == 1, jnp.take(lab, lab_idx, axis=1),
                    blank)  # (B, S)

    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (pos[None, :] % 2 == 1) & (ext != ext_prev2)

    # mask out positions beyond 2*len+1
    s_valid = pos[None, :] < (2 * lens[:, None] + 1)

    def step(alpha, logp_t):
        # logp_t: (B, C); emission per extended position
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (B, S)
        from_same = alpha
        from_prev = jnp.concatenate(
            [jnp.full((B, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        from_skip = jnp.concatenate(
            [jnp.full((B, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        from_skip = jnp.where(can_skip, from_skip, _NEG_INF)
        tot = jnp.logaddexp(jnp.logaddexp(from_same, from_prev), from_skip)
        new_alpha = jnp.where(s_valid, tot + emit, _NEG_INF)
        return new_alpha, new_alpha

    alpha0 = jnp.full((B, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_emit = jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lens > 0, first_emit, _NEG_INF))

    _, alphas = jax.lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)

    # read the final alpha at each example's last valid frame
    final = alphas[jnp.clip(t_lens - 1, 0, T - 1), jnp.arange(B)]  # (B, S)
    last = 2 * lens  # blank after last label
    ll_blank = jnp.take_along_axis(final, last[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(
        final, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    ll_label = jnp.where(lens > 0, ll_label, _NEG_INF)
    return -jnp.logaddexp(ll_blank, ll_label)
