"""Operator registry — the TPU-native replacement for NNVM op registration.

Reference: src/operator/ registers ops via NNVM_REGISTER_OP with separate
FCompute / FInferShape / FInferType / FGradient attributes
(include/mxnet/op_attr_types.h:197-282). On TPU none of those need to be
hand-written: each op here is a single pure JAX function, so

* shape/dtype inference  = ``jax.eval_shape`` over the same function,
* gradients              = JAX autodiff (or ``jax.custom_vjp`` where MXNet
                           semantics differ, e.g. SoftmaxOutput),
* kernel fusion/placement = XLA, with Pallas kernels for ops XLA can't fuse.

The registered function's signature declares its interface:
positional-or-keyword parameters are tensor inputs (``=None`` marks them
optional), keyword-only parameters are op attributes (the analog of
DMLC_REGISTER_PARAMETER structs, auto-documented through Python signatures).
"""
from __future__ import annotations

import functools
import inspect
import threading

import jax

from ..base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "OpContext",
           "op_context", "current_op_context"]

_OP_REGISTRY: dict[str, "OpDef"] = {}


class OpContext(threading.local):
    """Execution context threaded through op impls (trace-safe).

    Replaces the reference's OpContext (include/mxnet/op_attr_types.h:64:
    is_train, RunContext, requested resources). Random ops draw keys from
    here — the analog of ResourceRequest::kRandom (src/resource.cc:87).
    """

    def __init__(self):
        super().__init__()
        self.is_train = False
        self._rng_key = None
        self._rng_counter = 0

    def set(self, is_train, rng_key):
        self.is_train = is_train
        self._rng_key = rng_key
        self._rng_counter = 0

    def next_rng_key(self):
        if self._rng_key is None:
            # Eager fallback: draw from the global seed state lazily to avoid
            # an import cycle (mxnet_tpu.random imports the op registry).
            from .. import random as _random
            return _random.next_key()
        key = jax.random.fold_in(self._rng_key, self._rng_counter)
        self._rng_counter += 1
        return key


op_context = OpContext()


def current_op_context() -> OpContext:
    return op_context


class _OpCtxScope:
    """Context manager installing (is_train, rng_key) for a traced region."""

    def __init__(self, is_train, rng_key):
        self._new = (is_train, rng_key)

    def __enter__(self):
        self._saved = (op_context.is_train, op_context._rng_key,
                       op_context._rng_counter)
        op_context.set(*self._new)
        return op_context

    def __exit__(self, *a):
        (op_context.is_train, op_context._rng_key,
         op_context._rng_counter) = self._saved


class OpDef:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (MXNet CamelCase or snake_case as registered)
    fn : pure function (*tensor_inputs, **attrs) -> array or tuple of arrays
    input_names : declared tensor input names
    optional_inputs : subset of input_names that may be None
    attr_names : attribute (param) names
    num_outputs : static output count, or a callable(attrs)->int
    num_visible_outputs : outputs returned to the user in eager mode
    variadic : accepts *args tensor inputs (e.g. Concat, add_n)
    mutate_inputs : indices of inputs updated in place in eager mode
        (aux states like BatchNorm moving stats; optimizer update ops)
    """

    def __init__(self, name, fn, *, aliases=(), num_outputs=1,
                 num_visible_outputs=None, mutate_inputs=(), key_var_num_args=None):
        self.name = name
        self.fn = fn
        self.aliases = tuple(aliases)
        self.num_outputs = num_outputs
        self.num_visible_outputs = (num_visible_outputs
                                    if num_visible_outputs is not None
                                    else num_outputs)
        self.mutate_inputs = tuple(mutate_inputs)
        self.key_var_num_args = key_var_num_args
        # param_shapes(known_shapes: dict, attrs) -> dict of inferred input
        # shapes — the analog of the backward direction of FInferShape.
        self.param_shapes = None
        # unused_inputs(attrs) -> set of input names absent given these attrs
        # (e.g. FullyConnected bias when no_bias=True).
        self.unused_inputs = None
        # kw_input_order(attrs) -> ordered input names, for variadic ops
        # whose tensor inputs may be passed by keyword (Custom: the prop's
        # list_arguments order)
        self.kw_input_order = None

        sig = inspect.signature(fn)
        self.input_names = []
        self.optional_inputs = set()
        self.attr_names = []
        self.attr_defaults = {}
        self.variadic = False
        self.var_keyword = False  # op takes **kwargs attrs (Custom)
        for pname, p in sig.parameters.items():
            if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                          inspect.Parameter.POSITIONAL_OR_KEYWORD):
                self.input_names.append(pname)
                if p.default is None:
                    self.optional_inputs.add(pname)
            elif p.kind == inspect.Parameter.VAR_POSITIONAL:
                self.variadic = True
                self.varname = pname
            elif p.kind == inspect.Parameter.KEYWORD_ONLY:
                self.attr_names.append(pname)
                if p.default is not inspect.Parameter.empty:
                    self.attr_defaults[pname] = p.default
            elif p.kind == inspect.Parameter.VAR_KEYWORD:
                self.var_keyword = True
        self.__doc__ = fn.__doc__

    # ------------------------------------------------------------------
    def split_kwargs(self, kwargs):
        """Split user kwargs into (tensor_inputs_by_name, attrs)."""
        inputs, attrs = {}, {}
        for k, v in kwargs.items():
            if k in self.attr_names:
                attrs[k] = v
            elif k in self.input_names:
                inputs[k] = v
            elif self.var_keyword:
                # free-form op (Custom): tensors go to inputs, the rest
                # are prop attrs — classify by value type
                if _is_tensor_like(v):
                    inputs[k] = v
                else:
                    attrs[k] = v
            elif self.variadic:
                inputs[k] = v
            else:
                raise MXNetError("%s got unknown argument '%s'" % (self.name, k))
        return inputs, attrs

    def normalize_attrs(self, attrs):
        """Fill defaults + coerce MXNet-style string attrs (from JSON)."""
        out = dict(self.attr_defaults)
        for k, v in attrs.items():
            if k not in self.attr_names:
                if self.var_keyword:
                    # free-form attrs (Custom op params) stay as given;
                    # the prop receives them as strings like the reference
                    out[k] = v
                    continue
                raise MXNetError("%s: unknown attr '%s'" % (self.name, k))
            if isinstance(v, str):
                v = _parse_attr_string(v, self.attr_defaults.get(k))
            out[k] = v
        return out

    def ordered_kw_inputs(self, kw_inputs, attrs, n_positional=0):
        """Order keyword tensor inputs of a variadic op. With a declared
        ``kw_input_order`` (Custom), positional args fill the first
        ``n_positional`` slots; keyword names may not collide with them,
        may not be unknown, and must fill the remaining slots
        contiguously — anything else would silently bind tensors to the
        wrong arguments. Without a declared order (Concat, add_n, ...)
        keyword tensors simply append after the positional ones in name
        order (the pre-existing behavior; there are no names to check)."""
        if self.kw_input_order is None:
            return [kw_inputs[n] for n in sorted(kw_inputs)]
        order = self.kw_input_order(attrs)
        unknown = set(kw_inputs) - set(order)
        if unknown:
            raise MXNetError("%s: unexpected tensor input(s) %s (expected "
                             "from %s)" % (self.name, sorted(unknown), order))
        dup = set(kw_inputs) & set(order[:n_positional])
        if dup:
            raise MXNetError("%s: input(s) %s given both positionally and "
                             "by keyword" % (self.name, sorted(dup)))
        remaining = order[n_positional:]
        out = []
        for i, name in enumerate(remaining):
            if name in kw_inputs:
                if len(out) != i:
                    raise MXNetError(
                        "%s: keyword input '%s' given but earlier input "
                        "'%s' missing" % (self.name, name, remaining[i - 1]))
                out.append(kw_inputs[name])
        return out

    def out_count(self, attrs):
        n = self.num_outputs
        return n(attrs) if callable(n) else n

    def visible_out_count(self, attrs):
        n = self.num_visible_outputs
        return n(attrs) if callable(n) else n

    def __repr__(self):
        return "<OpDef %s>" % self.name


def _is_tensor_like(v):
    import numpy as _np
    if isinstance(v, (jax.Array, _np.ndarray)):
        return True
    cls = type(v).__mro__
    return any(c.__name__ in ("NDArray", "Symbol") for c in cls)


def _parse_attr_string(v, default):
    """Parse MXNet JSON attr strings: 'True', '(2, 2)', '1e-3', 'relu'."""
    s = v.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low == "none":
        return None
    if s.startswith("(") or s.startswith("["):
        inner = s[1:-1].strip()
        if not inner:
            return ()
        # "(8,)" has a trailing comma — skip empty segments
        return tuple(_parse_attr_string(t, None) for t in inner.split(",")
                     if t.strip())
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return v


def register(name=None, **opts):
    """Decorator registering an op. See OpDef for ``opts``."""

    def deco(fn):
        opname = name or fn.__name__
        opdef = OpDef(opname, fn, **opts)
        if opname in _OP_REGISTRY:
            raise MXNetError("op '%s' registered twice" % opname)
        _OP_REGISTRY[opname] = opdef
        for alias in opdef.aliases:
            _OP_REGISTRY[alias] = opdef
        return fn

    return deco


def add_alias(name, *aliases):
    """Register additional alias names for an existing op (the analog of
    NNVM ``.add_alias`` applied after the fact — used for the legacy
    CamelCase names the reference keeps for 0.x compatibility)."""
    opdef = get_op(name)
    for alias in aliases:
        existing = _OP_REGISTRY.get(alias)
        if existing is not None and existing is not opdef:
            raise MXNetError("alias '%s' already registered to '%s'"
                             % (alias, existing.name))
        _OP_REGISTRY[alias] = opdef
        if alias not in opdef.aliases:
            opdef.aliases = opdef.aliases + (alias,)


def get_op(name) -> OpDef:
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("operator '%s' is not registered" % name) from None


def has_op(name) -> bool:
    return name in _OP_REGISTRY


def list_ops():
    return sorted(_OP_REGISTRY)


def canonical_ops():
    """Unique OpDefs (aliases deduplicated)."""
    seen = {}
    for opdef in _OP_REGISTRY.values():
        seen.setdefault(id(opdef), opdef)
    return list(seen.values())
