"""Operator corpus — pure-JAX implementations behind the registry.

Importing this package registers all ops (the analog of the reference's
static NNVM_REGISTER_OP initializers across src/operator/)."""
from . import registry
from .registry import register, get_op, list_ops, OpDef

from . import elemwise      # noqa: F401
from . import tensor        # noqa: F401
from . import nn            # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import random_ops    # noqa: F401
from . import rnn           # noqa: F401
from . import custom        # noqa: F401
from . import contrib_ops   # noqa: F401
from . import quantization_ops  # noqa: F401
from . import extra         # noqa: F401
from . import tail_ops      # noqa: F401
from . import rcnn          # noqa: F401
from . import fused         # noqa: F401
from . import shape_rules   # noqa: F401

__all__ = ["registry", "register", "get_op", "list_ops", "OpDef"]
