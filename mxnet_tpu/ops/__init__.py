"""Operator corpus — pure-JAX implementations behind the registry.

Importing this package registers every op family (the analog of the
reference's static NNVM_REGISTER_OP initializers across src/operator/).
"""
from . import registry
from .registry import OpDef, get_op, list_ops, register
# Each family module self-registers on import; order only matters for the
# few families that extend earlier ones (fused/shape_rules go last).
from . import (elemwise, tensor, nn, optimizer_ops, random_ops, rnn,  # noqa: F401
               custom, contrib_ops, quantization_ops, extra, tail_ops,
               rcnn, fused, shape_rules)

__all__ = ["registry", "register", "get_op", "list_ops", "OpDef"]
