"""Long-tail operator corpus: linalg, ROI, spatial transform, misc.

Reference parity: src/operator/tensor/la_op.cc (_linalg_* family over
LAPACK/cuSOLVER — here jnp.linalg/lax.linalg, which XLA lowers to its
native decompositions), src/operator/roi_pooling.cc +
contrib/roi_align.cc (detection feature extraction),
src/operator/spatial_transformer.cc + grid_generator.cc,
contrib/{fft,ifft,quadratic,bounding_box}.cc, image/image_random.cc,
and assorted tensor ops (histogram, ravel/unravel, reshape_like,
khatri_rao, SVMOutput, legacy *_v1 aliases).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, get_op


# ----------------------------------------------------------------------
# linalg (la_op.cc) — all operate on (..., m, n) batches like the ref
# ----------------------------------------------------------------------
@register("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False,
                alpha=1.0, beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * (a @ b) + beta * C


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * (a @ b)


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A):
    """Cholesky factor (lower) — ref la_op.cc potrf."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A):
    """Inverse from the Cholesky factor: A is L, returns (L L^T)^-1."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = lax.linalg.triangular_solve(A, eye, left_side=True, lower=True)
    return jnp.swapaxes(linv, -1, -2) @ linv


@register("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    # BLAS trmm references only the named triangle of A
    a = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
    out = (B @ a) if rightside else (a @ B)
    return alpha * out


@register("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    out = lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)
    return out


@register("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (a @ jnp.swapaxes(a, -1, -2))


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization (ref la_op gelqf): A = L Q with Q row-orthonormal."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition; returns (U, lambda) with rows of U
    the eigenvectors (ref la_op syevd: A = U^T diag(l) U)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.log(diag).sum(axis=-1)


@register("khatri_rao")
def khatri_rao(*matrices):
    """Column-wise Kronecker product (ref contrib/krprod.cc)."""
    out = matrices[0]
    for m in matrices[1:]:
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, out.shape[1])
    return out


# ----------------------------------------------------------------------
# ROI feature extraction (roi_pooling.cc, contrib/roi_align.cc)
# ----------------------------------------------------------------------
@register("ROIPooling")
def roi_pooling(data, rois, *, pooled_size, spatial_scale=1.0):
    """Max-pool each ROI to a fixed grid (ref roi_pooling.cc). rois:
    (R, 5) rows [batch_idx, x1, y1, x2, y2] in image coords.

    Implementation: separable per-axis masked max — stage 1 reduces the
    H axis into ph row-bins, stage 2 reduces W into pw col-bins
    (O((ph+pw)·C·H·W) compute, O(C·H·W) memory). Correct in every
    regime incl. overlapping floor/ceil bin bounds and pooled grids
    finer than the ROI (where a pixel belongs to several bins)."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    H, W = data.shape[2], data.shape[3]

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = data[b]  # (C, H, W)

        def axis_mask(coords, p1, extent, i, nbins):
            lo = p1 + (i * extent) // nbins
            hi = p1 + ((i + 1) * extent + nbins - 1) // nbins
            return (coords >= lo) & (coords < hi)

        ys = jnp.arange(H)
        xs = jnp.arange(W)
        # stage 1: (C, H, W) -> (C, ph, W)
        rows = [jnp.where(axis_mask(ys, y1, rh, i, ph)[None, :, None],
                          img, -jnp.inf).max(axis=1) for i in range(ph)]
        stage1 = jnp.stack(rows, axis=1)
        # stage 2: (C, ph, W) -> (C, ph, pw)
        cols = [jnp.where(axis_mask(xs, x1, rw, j, pw)[None, None, :],
                          stage1, -jnp.inf).max(axis=2)
                for j in range(pw)]
        out = jnp.stack(cols, axis=2)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty cells -> 0

    return jax.vmap(one)(rois)


@register("_contrib_ROIAlign", aliases=("ROIAlign",))
def roi_align(data, rois, *, pooled_size, spatial_scale=1.0,
              sample_ratio=2):
    """Bilinear ROI align (ref contrib/roi_align.cc), avg mode."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    s = max(int(sample_ratio), 1)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[i] * spatial_scale for i in range(1, 5))
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        img = data[b]  # (C, H, W)
        # sample points: s per bin side
        iy = (jnp.arange(ph * s) + 0.5) / s  # in bin units
        ix = (jnp.arange(pw * s) + 0.5) / s
        ys = y1 + iy * rh / ph
        xs = x1 + ix * rw / pw
        from jax.scipy.ndimage import map_coordinates
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")

        def chan(c):
            # zero contribution outside the map (reference roi_align.cc
            # bilinear_interpolate returns 0 out of bounds)
            return map_coordinates(c, [gy, gx], order=1, mode="constant",
                                   cval=0.0)

        samp = jax.vmap(chan)(img)  # (C, ph*s, pw*s)
        return samp.reshape(img.shape[0], ph, s, pw, s).mean(axis=(2, 4))

    return jax.vmap(one)(rois)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, *, format="corner"):
    """Pairwise IoU (ref contrib/bounding_box.cc box_iou)."""
    def corners(b):
        if format == "center":
            return jnp.stack([b[..., 0] - b[..., 2] / 2,
                              b[..., 1] - b[..., 3] / 2,
                              b[..., 0] + b[..., 2] / 2,
                              b[..., 1] + b[..., 3] / 2], axis=-1)
        return b

    a = corners(lhs).reshape(-1, 4)
    b = corners(rhs).reshape(-1, 4)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    ar_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    ar_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    union = ar_a[:, None] + ar_b[None, :] - inter
    out = jnp.where(union > 0, inter / union, 0.0)
    return out.reshape(lhs.shape[:-1] + rhs.shape[:-1])


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          num_outputs=2)
def bipartite_matching(data, *, threshold, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a score matrix (ref bounding_box.cc).
    Returns (row->col match, col->row match), -1 for unmatched."""
    rows, cols = data.shape[-2], data.shape[-1]
    flat = data.reshape(-1, rows, cols)

    def one(mat):
        order_val = mat if is_ascend else -mat
        n_iter = rows if topk <= 0 else min(topk, rows)

        def body(carry, _):
            m, row_done, col_done = carry
            masked = jnp.where(row_done[:, None] | col_done[None, :],
                               jnp.inf, order_val)
            idx = jnp.argmin(masked)
            r, c = idx // cols, idx % cols
            ok = jnp.isfinite(masked[r, c]) & (
                (mat[r, c] >= threshold) if not is_ascend
                else (mat[r, c] <= threshold))
            m = m.at[r].set(jnp.where(ok, c, m[r]))
            row_done = row_done.at[r].set(row_done[r] | ok)
            col_done = col_done.at[c].set(col_done[c] | ok)
            return (m, row_done, col_done), None

        init = (jnp.full((rows,), -1.0), jnp.zeros((rows,), bool),
                jnp.zeros((cols,), bool))
        (m, _, _), _ = lax.scan(body, init, None, length=n_iter)
        cmatch = jnp.full((cols,), -1.0)
        valid = m >= 0
        cmatch = cmatch.at[jnp.where(valid, m, cols).astype(jnp.int32)].set(
            jnp.where(valid, jnp.arange(rows, dtype=jnp.float32), -1.0),
            mode="drop")
        return m, cmatch

    a, b = jax.vmap(one)(flat)
    return (a.reshape(data.shape[:-1]),
            b.reshape(data.shape[:-2] + (cols,)))


# ----------------------------------------------------------------------
# spatial transformer (grid_generator.cc, spatial_transformer.cc)
# ----------------------------------------------------------------------
@register("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=()):
    """Sampling grid (ref grid_generator.cc). ``affine``: data (N, 6)
    affine params -> grid (N, 2, H, W) of normalized (x, y) coords.
    ``warp``: data (N, 2, H, W) pixel-offset flow added to the identity
    grid, normalized to [-1, 1] (grid_generator-inl.h warp kernel)."""
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx, gy, ones], 0).reshape(3, -1)  # (3, H*W)
        out = theta @ coords  # (N, 2, H*W)
        return out.reshape(-1, 2, h, w)
    if transform_type == "warp":
        # identity grid built in f32: low-precision dtypes (bf16) can't
        # represent pixel indices past 256 exactly
        h, w = int(data.shape[2]), int(data.shape[3])
        gx = jnp.broadcast_to(jnp.arange(w, dtype=jnp.float32), (h, w))
        gy = jnp.broadcast_to(jnp.arange(h, dtype=jnp.float32)[:, None],
                              (h, w))
        x = (data[:, 0].astype(jnp.float32) + gx) * (2.0 / max(w - 1, 1)) - 1.0
        y = (data[:, 1].astype(jnp.float32) + gy) * (2.0 / max(h - 1, 1)) - 1.0
        return jnp.stack([x, y], axis=1).astype(data.dtype)
    raise ValueError("transform_type must be 'affine' or 'warp'")


@register("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape=(),
                        transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    """Affine-warp data with a learnt transform (ref
    spatial_transformer.cc)."""
    grid = grid_generator(loc, transform_type=transform_type,
                          target_shape=target_shape)
    return get_op("BilinearSampler").fn(data, grid)


# ----------------------------------------------------------------------
# resize / adaptive pooling / image ops
# ----------------------------------------------------------------------
@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None):
    h = int(height) if height else int(data.shape[2] * scale_height)
    w = int(width) if width else int(data.shape[3] * scale_width)
    return jax.image.resize(data, data.shape[:2] + (h, w), "bilinear")


@register("_contrib_AdaptiveAvgPooling2D",
          aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, *, output_size=()):
    if not output_size:
        return data.mean(axis=(2, 3), keepdims=True)
    if isinstance(output_size, int):
        oh = ow = int(output_size)
    else:
        oh = int(output_size[0])
        ow = int(output_size[1]) if len(output_size) > 1 else oh
    # exact adaptive bins: cell (i, j) averages rows [i*H//oh,
    # ceil((i+1)*H/oh)) etc. — matches the reference/torch definition
    H, W = data.shape[2], data.shape[3]
    rows = [data[:, :, (i * H) // oh:((i + 1) * H + oh - 1) // oh, :]
            .mean(axis=2) for i in range(oh)]
    stacked = jnp.stack(rows, axis=2)  # (N, C, oh, W)
    cols = [stacked[:, :, :, (j * W) // ow:((j + 1) * W + ow - 1) // ow]
            .mean(axis=3) for j in range(ow)]
    return jnp.stack(cols, axis=3)


@register("_image_to_tensor", aliases=("image_to_tensor",))
def image_to_tensor(data):
    """HWC uint8 [0,255] -> CHW float [0,1] (ref image_random.cc)."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize", aliases=("image_normalize",))
def image_normalize(data, *, mean=(0.0,), std=(1.0,)):
    m = jnp.asarray(mean, jnp.float32).reshape(-1, 1, 1)
    s = jnp.asarray(std, jnp.float32).reshape(-1, 1, 1)
    if data.ndim == 4:
        m, s = m[None], s[None]
    return (data - m) / s


# ----------------------------------------------------------------------
# misc tensor ops
# ----------------------------------------------------------------------
@register("_histogram", aliases=("histogram",), num_outputs=2)
def histogram(data, bins=None, *, bin_cnt=None, range=None):
    if bins is not None and bin_cnt is None:
        hist, edges = jnp.histogram(data.reshape(-1), bins=bins)
        return hist, edges
    cnt = int(bin_cnt) if bin_cnt else 10
    if range:
        lo, hi = range
    else:
        # traced min/max keep the op jit/graph-safe
        lo, hi = data.min(), data.max()
    hist, edges = jnp.histogram(data.reshape(-1), bins=cnt,
                                range=(lo, hi))
    return hist, edges


@register("_ravel_multi_index", aliases=("ravel_multi_index",))
def ravel_multi_index(data, *, shape):
    idx = [data[i].astype(jnp.int32) for i in range(data.shape[0])]
    out = jnp.zeros_like(idx[0])
    for i, s in enumerate(shape):
        out = out * int(s) + idx[i]
    return out.astype(jnp.float32)


@register("_unravel_index", aliases=("unravel_index",))
def unravel_index(data, *, shape):
    rem = data.astype(jnp.int32)
    outs = []
    for s in reversed(shape):
        outs.append(rem % int(s))
        rem = rem // int(s)
    return jnp.stack(list(reversed(outs)), axis=0).astype(jnp.float32)


@register("reshape_like")
def reshape_like(lhs, rhs):
    return lhs.reshape(rhs.shape)


@register("SVMOutput")
def svm_output(data, label, *, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Hinge-loss output head (ref svm_output.cc): forward is identity
    (scores); the gradient implements the (squared) hinge loss."""
    @jax.custom_vjp
    def f(x, lab):
        return x

    def fwd(x, lab):
        return x, (x, lab)

    def bwd(res, g):
        x, lab = res
        n, c = x.shape
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), c, dtype=x.dtype)
        # margin violation per class: score_j - score_y + margin > 0
        correct = (x * onehot).sum(axis=1, keepdims=True)
        viol = (x - correct + margin > 0) & (onehot == 0)
        if use_linear:
            gx = jnp.where(viol, regularization_coefficient, 0.0)
        else:
            gx = jnp.where(viol,
                           2.0 * regularization_coefficient
                           * (x - correct + margin), 0.0)
        gx = gx - gx.sum(axis=1, keepdims=True) * onehot
        return (gx.astype(x.dtype), jnp.zeros_like(lab))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("_contrib_fft", aliases=("fft",))
def fft(data, *, compute_size=128):
    """Real->complex FFT over the last axis, interleaved re/im layout
    (ref contrib/fft.cc: output last dim is 2x input)."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],))


@register("_contrib_ifft", aliases=("ifft",))
def ifft(data, *, compute_size=128):
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    # reference ifft is unnormalized (scale by n like cuFFT)
    return jnp.fft.ifft(comp, axis=-1).real * n


@register("_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """The contrib example op: a*x^2 + b*x + c (ref quadratic_op.cc)."""
    return a * jnp.square(data) + b * data + c


def _register_legacy_aliases():
    """BatchNorm_v1 / Convolution_v1 / Pooling_v1 behave like the modern
    ops for all supported options (the reference kept both registrations
    during migration; here they share one implementation)."""
    from .registry import _OP_REGISTRY
    for legacy, modern in (("BatchNorm_v1", "BatchNorm"),
                           ("Convolution_v1", "Convolution"),
                           ("Pooling_v1", "Pooling")):
        if legacy not in _OP_REGISTRY:
            _OP_REGISTRY[legacy] = _OP_REGISTRY[modern]


_register_legacy_aliases()


@register("Crop")
def crop(data, crop_like=None, *, offset=(0, 0), h_w=(0, 0),
         center_crop=False, num_args=1):
    """Legacy spatial crop (ref src/operator/crop.cc): crop data's H/W to
    ``h_w`` (or to crop_like's spatial dims) at ``offset`` or centered."""
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    if oy < 0 or ox < 0 or oy + th > H or ox + tw > W:
        # the reference CHECKs bounds; silent slice clamping would
        # surface as a confusing downstream shape mismatch
        raise ValueError("Crop out of bounds: offset (%d, %d) + size "
                         "(%d, %d) exceeds input (%d, %d)"
                         % (oy, ox, th, tw, H, W))
    return data[:, :, oy:oy + th, ox:ox + tw]


def _crop_unused(attrs):
    return {"crop_like"} if int(attrs.get("num_args", 1)) < 2 else set()


get_op("Crop").unused_inputs = _crop_unused


@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",),
          num_outputs=5, num_visible_outputs=1,
          mutate_inputs=(("moving_mean", 3), ("moving_var", 4)))
def sync_batch_norm(data, gamma, beta, moving_mean=None, moving_var=None,
                    *, eps=1e-3, momentum=0.9, fix_gamma=True,
                    use_global_stats=False, key=None, ndev=1):
    """Cross-device synchronized BatchNorm (ref
    contrib/sync_batch_norm.cc). Under GSPMD batch sharding the batch
    statistics reductions are already global — XLA inserts the
    cross-device collectives — so this forwards to BatchNorm; ``key``
    and ``ndev`` (the reference's comm handle) are accepted and
    unused."""
    return get_op("BatchNorm").fn(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats)
