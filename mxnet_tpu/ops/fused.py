"""Fused BN→ReLU→1×1-Conv operator with a Pallas TPU kernel.

The single-chip ResNet step is HBM-bandwidth-bound (docs/ROOFLINE.md):
its top ops by device time are elementwise/reduce fusions sustaining
540–740 GB/s with negligible FLOPs. XLA fuses elementwise chains with
each other and into conv *outputs*, but it does not fuse an elementwise
producer into a convolution's *input* operand — so every BN-apply+ReLU
before a conv costs one full activation read + write that the MXU pass
then reads again. This module deletes that pass for the 1×1 convolutions
(2 of every 3 convs in a ResNet bottleneck):

    y = relu(x · scale + shift) @ W  (+ residual)

runs as ONE Pallas kernel: the per-channel affine (BN apply) and ReLU
happen in VMEM on the tile the MXU is about to consume, so ``x`` is read
exactly once and the ReLU'd activation never exists in HBM. The BN
*stats* pass stays in XLA (sum/sum² multi-output-fuse into one read);
``scale``/``shift`` are computed from (γ, β, mean, var) in plain jnp, so
JAX autodiff assembles the full BatchNorm backward through the stats —
the custom VJP here only supplies the big-tensor passes.

Reference parity: this replaces the composition BatchNorm → Activation →
Convolution(1×1) (src/operator/nn/batch_norm.cc, activation.cc,
convolution.cc); the graph rewrite lives in symbol/fuse.py (the TPU
analog of a graph-executor fusion pass, graph_executor.cc:905's
memory-plan/bulking stage being XLA's job already).
"""
from __future__ import annotations

import os
from functools import partial

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, current_op_context

__all__ = ["fused_scale_relu_matmul", "fused_bn_relu_conv"]


def _pallas_wanted():
    """Pallas only on real TPU backends (the CPU test mesh and the
    multichip dryrun use the jnp fallback — same math, same VJP)."""
    mode = os.environ.get("MXTPU_FUSED_PALLAS", "auto")
    if mode in ("0", "off"):
        return False
    if mode in ("1", "on", "interpret"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # no backend yet
        return False


def _interpret_mode():
    return os.environ.get("MXTPU_FUSED_PALLAS", "auto") == "interpret"


def _pick_tile_m(m):
    for tm in (512, 256, 128):
        if m % tm == 0:
            return tm
    return None


def _matmul_kernel(x_ref, scale_ref, shift_ref, w_ref, out_ref, *,
                   relu, out_dtype):
    xf = x_ref[:].astype(jnp.float32)
    z = xf * scale_ref[:] + shift_ref[:]
    if relu:
        z = jnp.maximum(z, 0.0)
    a = z.astype(w_ref.dtype)
    acc = jnp.dot(a, w_ref[:], preferred_element_type=jnp.float32)
    out_ref[:] = acc.astype(out_dtype)


def _matmul_res_kernel(x_ref, scale_ref, shift_ref, w_ref, res_ref,
                       out_ref, *, relu, out_dtype):
    xf = x_ref[:].astype(jnp.float32)
    z = xf * scale_ref[:] + shift_ref[:]
    if relu:
        z = jnp.maximum(z, 0.0)
    a = z.astype(w_ref.dtype)
    acc = jnp.dot(a, w_ref[:], preferred_element_type=jnp.float32)
    acc = acc + res_ref[:].astype(jnp.float32)
    out_ref[:] = acc.astype(out_dtype)


def _pallas_fwd(x2d, scale, shift, w2d, res):
    """One-pass relu(x·scale+shift) @ W (+res) on the MXU; grid over row
    tiles, weights resident in VMEM across the grid."""
    from jax.experimental import pallas as pl

    from ..pallas.attention import _count_launch

    m, k = x2d.shape
    n = w2d.shape[1]
    tm = _pick_tile_m(m)
    if tm is None:
        return None
    grid = (m // tm,)
    scale2 = scale.reshape(1, k).astype(jnp.float32)
    shift2 = shift.reshape(1, k).astype(jnp.float32)
    in_specs = [
        pl.BlockSpec((tm, k), lambda i: (i, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec((k, n), lambda i: (0, 0)),
    ]
    args = [x2d, scale2, shift2, w2d]
    if res is not None:
        kern = partial(_matmul_res_kernel, relu=True, out_dtype=x2d.dtype)
        in_specs.append(pl.BlockSpec((tm, n), lambda i: (i, 0)))
        args.append(res)
    else:
        kern = partial(_matmul_kernel, relu=True, out_dtype=x2d.dtype)
    _count_launch("fused_scale_relu_matmul")
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
        interpret=_interpret_mode(),
    )(*args)


def _jnp_fwd(x2d, scale, shift, w2d, res):
    z = x2d.astype(jnp.float32) * scale + shift
    a = jnp.maximum(z, 0.0).astype(x2d.dtype)
    y = lax.dot_general(a, w2d, (((1,), (0,)), ((), ())))
    if res is not None:
        y = y + res
    return y.astype(x2d.dtype)


def _core_fwd(x2d, scale, shift, w2d, res):
    if _pallas_wanted():
        out = _pallas_fwd(x2d, scale, shift, w2d, res)
        if out is not None:
            return out
    return _jnp_fwd(x2d, scale, shift, w2d, res)


@partial(jax.custom_vjp, nondiff_argnums=())
def _core(x2d, scale, shift, w2d, res):
    return _core_fwd(x2d, scale, shift, w2d, res)


def _core_fwd_rule(x2d, scale, shift, w2d, res):
    y = _core_fwd(x2d, scale, shift, w2d, res)
    return y, (x2d, scale, shift, w2d, None if res is None else ())


def _core_bwd_rule(saved, dy):
    x2d, scale, shift, w2d, res_tag = saved
    f32 = jnp.float32
    # dz = (dy @ W^T) masked by relu'(z); z recomputed from x (elementwise
    # producer XLA fuses into the matmul output's consumer chain)
    da = lax.dot_general(dy, w2d, (((1,), (1,)), ((), ())))
    z = x2d.astype(f32) * scale + shift
    dz = jnp.where(z > 0, da.astype(f32), 0.0)
    # per-channel affine grads: one fused multi-output reduction pass
    dscale = jnp.sum(dz * x2d.astype(f32), axis=0)
    dshift = jnp.sum(dz, axis=0)
    dx = (dz * scale).astype(x2d.dtype)
    # dW = a^T @ dy with a recomputed from x
    a = jnp.maximum(z, 0.0).astype(x2d.dtype)
    dw = lax.dot_general(a, dy, (((0,), (0,)), ((), ())))
    dres = None if res_tag is None else dy
    return (dx, dscale.astype(scale.dtype), dshift.astype(shift.dtype),
            dw.astype(w2d.dtype), dres)


_core.defvjp(_core_fwd_rule, _core_bwd_rule)


def fused_scale_relu_matmul(x2d, scale, shift, w2d, res=None):
    """relu(x·scale + shift) @ W (+res) — differentiable fused primitive.

    x2d (M, K); scale/shift (K,) fp32; w2d (K, N); res (M, N) or None
    (None is a static empty pytree, so both arities share one VJP).
    """
    return _core(x2d, scale, shift, w2d, res)


@register("_FusedBNReluConv", num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(("moving_mean", 1), ("moving_var", 2)))
def fused_bn_relu_conv(data, gamma, beta, moving_mean, moving_var, weight,
                       residual=None, *, num_filter, eps=2e-5, momentum=0.9,
                       fix_gamma=False, use_global_stats=False, layout="NHWC",
                       with_residual=False):
    """BatchNorm → ReLU → Convolution(1×1, stride 1, no bias) fused into
    one MXU pass (channel-last only). Optional ``residual`` is added to
    the conv output inside the same kernel (the shortcut add of a
    post-activation ResNet block). Outputs (y, new_moving_mean,
    new_moving_var); the moving stats update exactly like BatchNorm
    (ops/nn.py batch_norm). Created by symbol/fuse.py's graph rewrite —
    not part of the reference op set (cited ops: batch_norm.cc,
    activation.cc, convolution.cc)."""
    if not str(layout).endswith("C"):
        raise ValueError("_FusedBNReluConv requires a channel-last layout")
    ctx = current_op_context()
    f32 = jnp.float32
    k = data.shape[-1]
    red = tuple(range(data.ndim - 1))

    if moving_mean is None:
        moving_mean = jnp.zeros((k,), f32)
    if moving_var is None:
        moving_var = jnp.ones((k,), f32)

    if ctx.is_train and not use_global_stats:
        n = 1
        for i in red:
            n *= data.shape[i]
        # one fused read: sum and sum² multi-output-fuse (docs/PERF.md);
        # differentiable, so autodiff carries the full BN-through-stats
        # backward — _core's VJP only supplies the big-tensor passes
        s = jnp.sum(data, axis=red, dtype=f32)
        s2 = jnp.sum(jnp.square(data.astype(f32)), axis=red)
        mean = s / n
        var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
        mean_s = lax.stop_gradient(mean)
        var_s = lax.stop_gradient(var)
        new_mm = (moving_mean.astype(f32) * momentum
                  + mean_s * (1 - momentum)).astype(moving_mean.dtype)
        new_mv = (moving_var.astype(f32) * momentum
                  + var_s * (1 - momentum)).astype(moving_var.dtype)
    else:
        mean = lax.stop_gradient(moving_mean.astype(f32))
        var = lax.stop_gradient(moving_var.astype(f32))
        new_mm, new_mv = moving_mean, moving_var

    inv_std = lax.rsqrt(var + eps)
    g32 = jnp.ones_like(inv_std) if fix_gamma else gamma.astype(f32)
    scale = g32 * inv_std
    shift = beta.astype(f32) - mean * scale

    o = int(num_filter)
    w2d = weight.reshape(o, k).T            # OHWI (O,1,1,K) -> (K,O)
    x2d = data.reshape(-1, k)
    out_shape = data.shape[:-1] + (o,)
    res2d = None
    post_add = None
    if with_residual and residual is not None:
        if residual.shape == out_shape:
            res2d = residual.reshape(-1, o)
        else:                               # broadcasting add: keep outside
            post_add = residual
    y2d = fused_scale_relu_matmul(x2d, scale, shift, w2d, res2d)
    y = y2d.reshape(out_shape)
    if post_add is not None:
        y = y + post_add
    return (y, lax.stop_gradient(new_mm), lax.stop_gradient(new_mv))


def _fused_shapes(known, attrs):
    """Backward shape rule: data (…, K) + num_filter O infer the BN
    vectors (K,) and the channel-last conv weight (O, 1, 1, K)."""
    data = known.get("data")
    if data is None:
        return {}
    k = data[-1]
    o = int(attrs["num_filter"])
    nd = len(data)
    out = {"gamma": (k,), "beta": (k,), "moving_mean": (k,),
           "moving_var": (k,),
           "weight": (o,) + (1,) * (nd - 2) + (k,)}
    if attrs.get("with_residual"):
        out["residual"] = tuple(data[:-1]) + (o,)
    return out


def _fused_unused(attrs):
    return set() if attrs.get("with_residual") else {"residual"}


from .registry import get_op as _get_op  # noqa: E402

_op = _get_op("_FusedBNReluConv")
_op.param_shapes = _fused_shapes
_op.unused_inputs = _fused_unused
