"""Fused multi-layer RNN operator (vanilla/LSTM/GRU, bidirectional).

Reference parity: src/operator/rnn-inl.h:150 (RNN op) with cuDNN's packed
flat-weight layout (src/operator/cudnn_rnn-inl.h): all layer weight matrices
first (per layer, per direction: W_i2h gates then R_h2h gates), then all
biases (b_W then b_R per layer/direction). Gate orders follow cuDNN:
LSTM (i, f, g, o), GRU (r, z, n).

TPU-native: one ``lax.scan`` per layer+direction — the whole multi-layer
unroll compiles to a single XLA while-loop with MXU-sized gate matmuls,
replacing the reference's cuDNN descriptor machinery.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, current_op_context
from .nn import needs_rng

_NGATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total flat parameter count — mirrors reference GetRnnParamSize
    (src/operator/rnn-inl.h:88)."""
    ngates = _NGATES[mode]
    ndir = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * ndir
        size += ndir * ngates * state_size * (isz + state_size + 2)
    return size


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    ngates = _NGATES[mode]
    ndir = 2 if bidirectional else 1
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * ndir
        for _ in range(ndir):
            w = params[off:off + ngates * state_size * isz].reshape(
                ngates * state_size, isz)
            off += w.size
            r = params[off:off + ngates * state_size * state_size].reshape(
                ngates * state_size, state_size)
            off += r.size
            ws.append((w, r))
    for layer in range(num_layers):
        for _ in range(ndir):
            bw = params[off:off + ngates * state_size]
            off += bw.size
            br = params[off:off + ngates * state_size]
            off += br.size
            bs.append((bw, br))
    return ws, bs


def _cell_step(mode, state_size):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new)
        return step
    if mode == "gru":
        return None  # handled specially (r gates h-projection)
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(carry, gates):
        (h,) = carry
        return (act(gates),)
    return step


def _run_layer(x, w, r, bw, br, h0, c0, mode, state_size, reverse):
    """x: (seq, batch, in). Returns (out (seq,batch,state), hT, cT)."""
    seq = x.shape[0]
    # big input matmul hoisted out of the scan → one MXU matmul over
    # (seq*batch, in) instead of seq small ones.
    xg = jnp.einsum("sbi,gi->sbg", x, w) + bw + 0.0
    if reverse:
        xg = jnp.flip(xg, axis=0)

    if mode == "gru":
        def scan_fn(carry, xg_t):
            (h,) = carry
            hproj = jnp.dot(h, r.T) + br
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hproj, 3, axis=-1)
            rt = jax.nn.sigmoid(xr + hr)
            zt = jax.nn.sigmoid(xz + hz)
            nt = jnp.tanh(xn + rt * hn)
            h_new = (1.0 - zt) * nt + zt * h
            return (h_new,), h_new
        (hT,), out = lax.scan(scan_fn, (h0,), xg)
        cT = None
    elif mode == "lstm":
        cell = _cell_step(mode, state_size)

        def scan_fn(carry, xg_t):
            h, c = carry
            gates = xg_t + jnp.dot(h, r.T) + br
            h_new, c_new = cell((h, c), gates)
            return (h_new, c_new), h_new
        (hT, cT), out = lax.scan(scan_fn, (h0, c0), xg)
    else:
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def scan_fn(carry, xg_t):
            (h,) = carry
            h_new = act(xg_t + jnp.dot(h, r.T) + br)
            return (h_new,), h_new
        (hT,), out = lax.scan(scan_fn, (h0,), xg)
        cT = None
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, hT, cT


def _rnn_num_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN", num_outputs=_rnn_num_outputs,
          num_visible_outputs=_rnn_num_outputs)
@needs_rng
def rnn(data, parameters, state, state_cell=None, *, state_size, num_layers,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, projection_size=None):
    """data (seq, batch, input); state (layers*dirs, batch, state_size)."""
    ctx = current_op_context()
    ndir = 2 if bidirectional else 1
    input_size = data.shape[2]
    # Batch-1 initial states broadcast to the data batch (the symbolic
    # cell zoo's default begin_state emits (L*D, 1, H) zeros; cuDNN-parity
    # callers pass the full batch). ONLY the batch axis broadcasts —
    # wrong layer/direction/hidden axes must still raise.
    full = (num_layers * ndir, data.shape[1], state_size)

    def _fit_state(s_, what):
        if s_.shape == full:
            return s_
        if s_.shape == (full[0], 1, full[2]):
            return jnp.broadcast_to(s_, full)
        raise ValueError(
            f"RNN {what} has shape {s_.shape}; expected {full} "
            f"or ({full[0]}, 1, {full[2]})")

    state = _fit_state(state, "state")
    if state_cell is not None:
        state_cell = _fit_state(state_cell, "state_cell")
    ws, bs = _unpack_params(parameters, num_layers, input_size, state_size,
                            bidirectional, mode)
    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(ndir):
            idx = layer * ndir + d
            w, r = ws[idx]
            bw, br = bs[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if (mode == "lstm" and state_cell is not None) \
                else None
            out, hT, cT = _run_layer(x, w, r, bw, br, h0, c0, mode,
                                     state_size, reverse=(d == 1))
            outs.append(out)
            h_outs.append(hT)
            if cT is not None:
                c_outs.append(cT)
        x = outs[0] if ndir == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and ctx.is_train and layer < num_layers - 1:
            key = ctx.next_rng_key()
            keep = 1.0 - p
            mask = jax.random.bernoulli(key, keep, x.shape).astype(x.dtype) / keep
            x = x * mask
    result = [x]
    if state_outputs:
        result.append(jnp.stack(h_outs, axis=0))
        if mode == "lstm":
            result.append(jnp.stack(c_outs, axis=0))
    return tuple(result) if len(result) > 1 else result[0]
