"""Tensor shape/index manipulation + init operators.

Reference parity: src/operator/tensor/{matrix_op.cc,indexing_op.cc,
init_op.cc,ordering_op.cc}. Reshapes/transposes are free inside XLA; index
ops lower to gather/scatter HLOs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("Reshape", aliases=("reshape",))
def reshape(data, *, shape=(), reverse=False, target_shape=None, keep_highest=False):
    """MXNet reshape with magic values 0 (keep), -1 (infer), -2 (copy rest),
    -3 (merge two), -4 (split) — ref src/operator/tensor/matrix_op-inl.h:95."""
    ishape = data.shape
    if target_shape:  # legacy attr
        shape = target_shape
    shape = tuple(int(s) for s in shape)
    if reverse:
        rev = _infer_magic(tuple(reversed(ishape)), tuple(reversed(shape)))
        return jnp.reshape(data, tuple(reversed(rev)))
    return jnp.reshape(data, _infer_magic(ishape, shape))


def _infer_magic(ishape, shape):
    out = []
    i = 0  # index into ishape
    j = 0
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(ishape[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(ishape[i:]); i = len(ishape)
        elif s == -3:
            out.append(ishape[i] * ishape[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = ishape[i] // b
            if b == -1:
                b = ishape[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    return tuple(out)


@register("Flatten", aliases=("flatten",))
def flatten_op(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, *, axes=()):
    return jnp.transpose(data, tuple(axes) if axes else None)


@register("expand_dims")
def expand_dims(data, *, axis):
    return jnp.expand_dims(data, int(axis))


@register("squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis if axis is None else tuple(
        [axis] if isinstance(axis, int) else axis))


@register("SwapAxis", aliases=("swapaxes",))
def swapaxes(data, *, dim1=0, dim2=0):
    return jnp.swapaxes(data, int(dim1), int(dim2))


@register("Cast", aliases=("cast",))
def cast(data, *, dtype):
    return data.astype(dtype)


@register("Concat", aliases=("concat",), key_var_num_args="num_args")
def concat(*args, num_args=None, dim=1):
    return jnp.concatenate(args, axis=int(dim))


@register("stack", key_var_num_args="num_args")
def stack(*args, num_args=None, axis=0):
    return jnp.stack(args, axis=int(axis))


def _split_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


@register("SliceChannel", aliases=("split",), num_outputs=_split_outputs,
          num_visible_outputs=_split_outputs)
def slice_channel(data, *, num_outputs, axis=1, squeeze_axis=False):
    """Split along axis into equal parts (ref src/operator/slice_channel.cc)."""
    parts = jnp.split(data, int(num_outputs), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=int(axis)) for p in parts]
    return tuple(parts)


@register("slice", aliases=("crop",))
def slice_op(data, *, begin, end, step=()):
    idx = []
    step = tuple(step) if step else (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return data[tuple(idx)]


@register("slice_axis")
def slice_axis(data, *, axis, begin, end):
    axis = int(axis) % data.ndim
    if end is None:
        end = data.shape[axis]
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(int(begin), int(end))
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, *, axes=()):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    idx = [slice(None)] * data.ndim
    for ax in axes:
        idx[ax] = slice(0, shape_like.shape[ax])
    return data[tuple(idx)]


@register("take")
def take(a, indices, *, axis=0, mode="clip"):
    """Gather along axis (ref src/operator/tensor/indexing_op.cc)."""
    idx = indices.astype("int32")
    return jnp.take(a, idx, axis=int(axis), mode="clip" if mode == "clip" else "wrap")


@register("batch_take")
def batch_take(a, indices):
    idx = indices.astype("int32")
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("pick")
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    idx = index.astype("int32")
    ax = int(axis)
    idxe = jnp.expand_dims(idx, ax)
    out = jnp.take_along_axis(data, idxe, axis=ax)
    if not keepdims:
        out = jnp.squeeze(out, axis=ax)
    return out


@register("one_hot")
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype("int32"), int(depth), dtype=dtype)
    return oh * (on_value - off_value) + off_value


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype("int32"))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, *, shape):
    idx = tuple(indices.astype("int32"))
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[idx].add(data)


@register("tile")
def tile(data, *, reps):
    if isinstance(reps, int):
        reps = (reps,)
    return jnp.tile(data, tuple(reps))


@register("repeat")
def repeat(data, *, repeats, axis=None):
    return jnp.repeat(data, int(repeats), axis=None if axis is None else int(axis))


@register("Pad", aliases=("pad",))
def pad(data, *, mode="constant", pad_width=(), constant_value=0.0):
    """N-D padding (ref src/operator/pad.cc). pad_width is the MXNet flat
    (before, after) per-axis tuple."""
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1]))
          for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    return jnp.pad(data, pw, mode="reflect")


@register("reverse", aliases=("flip",))
def reverse(data, *, axis):
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else (int(axis),)
    return jnp.flip(data, axis=ax)


@register("broadcast_to")
def broadcast_to(data, *, shape):
    tgt = tuple(int(t) if int(t) != 0 else data.shape[i]
                for i, t in enumerate(shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, *, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("shape_array")
def shape_array(data):
    return jnp.asarray(data.shape, dtype="int64")


@register("size_array")
def size_array(data):
    return jnp.asarray([data.size], dtype="int64")


@register("depth_to_space")
def depth_to_space(data, *, block_size):
    b = int(block_size)
    n, c, h, w = data.shape
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, *, block_size):
    b = int(block_size)
    n, c, h, w = data.shape
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ----------------------------------------------------------------------
# ordering (ref src/operator/tensor/ordering_op.cc)
# ----------------------------------------------------------------------
@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=None if axis is None else int(axis))
    if not is_ascend:
        out = jnp.flip(out, axis=-1 if axis is None else int(axis))
    return out


@register("argsort")
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    x = data if is_ascend else -data
    out = jnp.argsort(x, axis=None if axis is None else int(axis), stable=True)
    return out.astype(dtype)


def _topk_nout(attrs):
    rt = attrs.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register("topk", num_outputs=_topk_nout, num_visible_outputs=_topk_nout)
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    if axis is None:
        # reference: axis=None flattens before ranking; a mask comes back
        # in the ORIGINAL shape
        flat = topk(data.reshape(-1), axis=-1, k=k, ret_typ=ret_typ,
                    is_ascend=is_ascend, dtype=dtype)
        if ret_typ == "mask":
            return flat.reshape(data.shape)
        return flat
    ax = int(axis) % data.ndim
    k = int(k) if int(k) > 0 else data.shape[ax]
    x = jnp.moveaxis(data, ax, -1)
    vals, raw_idxs = jax.lax.top_k(-x if is_ascend else x, k)
    if is_ascend:
        vals = -vals
    if ret_typ == "mask":
        # 0/1 mask marking the top-k entries (ordering.cc ret_typ=mask)
        onehot = jax.nn.one_hot(raw_idxs, x.shape[-1], dtype=data.dtype)
        return jnp.moveaxis(onehot.sum(axis=-2), -1, ax)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(raw_idxs, -1, ax).astype(dtype)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    return idxs


# ----------------------------------------------------------------------
# sequence ops (ref src/operator/sequence_*.cc)
# ----------------------------------------------------------------------
@register("SequenceMask")
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    ax = int(axis)  # sequence axis: 0 or 1; batch is the other of (0,1)
    seq = data.shape[ax]
    steps = jnp.arange(seq)
    lens = sequence_length.astype(steps.dtype)
    mask = steps[:, None] < lens[None, :]  # (seq, batch)
    if ax == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    ax = int(axis)
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[ax] - 1, axis=ax)
    idx = (sequence_length.astype("int32") - 1)
    x = jnp.moveaxis(data, ax, 0)  # (seq, batch, ...)
    batch = jnp.arange(x.shape[1])
    return x[idx, batch]


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    seq = data.shape[0]
    steps = jnp.arange(seq)[:, None]
    lens = sequence_length.astype("int32")[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)  # (seq,batch)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[rev_idx, batch]


# ----------------------------------------------------------------------
# init ops (ref src/operator/tensor/init_op.cc)
# ----------------------------------------------------------------------
@register("_zeros", aliases=("zeros_op",))
def _zeros(*, shape=(), dtype="float32", ctx=None):
    return jnp.zeros(tuple(shape), dtype=dtype or "float32")


@register("_ones")
def _ones(*, shape=(), dtype="float32", ctx=None):
    return jnp.ones(tuple(shape), dtype=dtype or "float32")


@register("_full")
def _full(*, shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(tuple(shape), value, dtype=dtype or "float32")


@register("_arange")
def _arange(*, start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None):
    out = jnp.arange(start, stop, step, dtype=dtype)
    if repeat and int(repeat) > 1:
        out = jnp.repeat(out, int(repeat))
    return out


@register("_eye", aliases=("eye",))
def _eye(*, N, M=0, k=0, dtype="float32", ctx=None):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=dtype)


@register("diag")
def diag(data, *, k=0):
    return jnp.diag(data, k=int(k)) if data.ndim <= 2 else jnp.diagonal(data, offset=int(k))


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """Per-row gather: out[i] = lhs[i, rhs[i]] (reference
    src/operator/tensor/broadcast_reduce_op_index.cc pick 0-index form)."""
    idx = rhs.astype(jnp.int32)
    return jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """Per-row scatter: out[i, rhs[i]] = mhs[i] (reference parity)."""
    idx = rhs.astype(jnp.int32)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(
        mhs.astype(lhs.dtype))


@register("_ndarray_getitem")
def _ndarray_getitem(data, *, key=None):
    """Basic/advanced indexing as a differentiable op — NDArray.__getitem__
    routes here while autograd records so sliced reads stay on the tape
    (the reference records its slice/gather kernels the same way)."""
    return data[key]
