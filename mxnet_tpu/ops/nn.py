"""Neural-network operators lowering to XLA.

Reference parity: src/operator/nn/ (fully_connected.cc:231, convolution.cc,
batch_norm.cc, pooling.cc, activation.cc, dropout-inl.h, layer_norm.cc,
softmax_output.cc, lrn.cc) and src/operator/tensor/indexing_op.cc(Embedding).

TPU-first notes: matmuls/convs map onto the MXU via lax.dot_general /
lax.conv_general_dilated; XLA layout assignment picks the TPU-internal
layout so the NCHW API surface carries no transpose cost. Ops that the
reference implements with cuDNN become single XLA HLOs here. Gradients come
from JAX autodiff except where MXNet semantics differ (SoftmaxOutput's
fused softmax-CE gradient → jax.custom_vjp).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, current_op_context


def needs_rng(fn):
    fn._needs_rng = True
    return fn


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        t = tuple(int(x) for x in v)
        return t if t else (1,) * n
    return (int(v),) * n


# ----------------------------------------------------------------------
# FullyConnected
# ----------------------------------------------------------------------
@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, *, num_hidden, no_bias=False,
                    flatten=True):
    """y = x W^T + b (ref src/operator/nn/fully_connected-inl.h:85-166).
    weight layout (num_hidden, in_dim) matches the reference."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    # bf16 inputs accumulate in fp32 on the MXU by default; no explicit
    # preferred_element_type (its transpose rule breaks mixed-dtype vjp)
    y = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())))
    if not no_bias and bias is not None:
        y = y + bias
    return y


# ----------------------------------------------------------------------
# Convolution / Deconvolution
# ----------------------------------------------------------------------
def _conv_dnums(ndim, layout=None):
    """(lhs, rhs, out) layout strings. ``layout`` is the MXNet layout
    attr for the DATA tensor; channel-last layouts pair with
    channel-last weights (num_filter, *kernel, in_ch/g), matching the
    reference's NHWC contract (convolution.cc layout param)."""
    if layout:
        layout = str(layout)
        if layout.endswith("C"):            # NWC / NHWC / NDHWC
            rhs = "O" + layout[1:-1] + "I"
            return (layout, rhs, layout)
        rhs = "OI" + layout[2:]             # NCW / NCHW / NCDHW
        return (layout, rhs, layout)
    if ndim == 3:
        return ("NCH", "OIH", "NCH")
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


def _channel_axis(ndim, layout=None):
    return (ndim - 1) if (layout and str(layout).endswith("C")) else 1


@register("Convolution", aliases=("convolution",))
def convolution(data, weight, bias=None, *, kernel, num_filter, stride=(),
                dilate=(), pad=(), num_group=1, no_bias=False, cudnn_tune=None,
                cudnn_off=False, workspace=1024, layout=None):
    """N-D convolution (ref src/operator/nn/convolution.cc). Lowers to a
    single conv HLO on the MXU; groups via feature_group_count. TPU-first:
    ``layout='NHWC'`` (channel-last data AND weights) avoids every
    relayout copy around the conv — the preferred training layout."""
    nd = len(kernel)
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dnums(data.ndim, layout))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    ).astype(data.dtype)
    if not no_bias and bias is not None:
        ax = _channel_axis(data.ndim, layout)
        bshape = tuple(-1 if i == ax else 1 for i in range(data.ndim))
        out = out + bias.reshape(bshape)
    return out


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, *, kernel, num_filter, stride=(),
                  dilate=(), pad=(), adj=(), target_shape=(), num_group=1,
                  no_bias=True, cudnn_tune=None, cudnn_off=False,
                  workspace=512, layout=None):
    """Transposed convolution (ref src/operator/nn/deconvolution.cc).
    weight layout (in_ch, out_ch/g, kh, kw); implemented as the gradient of
    conv = conv with lhs_dilation."""
    nd = len(kernel)
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    adj = _pair(adj, nd) if adj else (0,) * nd
    kernel = tuple(int(k) for k in kernel)
    # flip spatial dims; swap in/out channel axes → standard conv weight
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if int(num_group) > 1:
        g = int(num_group)
        w = w.reshape((g, w.shape[0] // g) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((w.shape[0] * w.shape[1],) + w.shape[2:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _conv_dnums(data.ndim))
    eff_k = tuple((kernel[i] - 1) * dilate[i] + 1 for i in range(nd))
    padding = [(eff_k[i] - 1 - pad[i], eff_k[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    ).astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * (data.ndim - 2))
    return out


# ----------------------------------------------------------------------
# BatchNorm
# ----------------------------------------------------------------------
def _bn_train_fused(red, bshape, eps, fix_gamma, n):
    """Training-mode batch norm as ONE fused stats pass + ONE apply pass,
    with a hand-derived backward (ONE reduction pass + ONE elementwise
    pass). The HBM-bandwidth-optimal schedule (docs/PERF.md):

    * stats: sum(x) and sum(x^2) are independent reductions over the same
      operand, so XLA multi-output-fuses them into a single read of the
      activation with fp32 accumulators (vs the naive mean-then-var
      serial double pass). var = E[x^2] - E[x]^2, the cuDNN "persistent"
      formulation.
    * apply/backward passes read and write the activation dtype (bf16 on
      TPU); fp32 math happens in registers inside the fusion, so no fp32
      copy of any activation ever hits HBM.

    Gradients for save_mean/save_var outputs are intentionally dropped
    (reference semantics: batch_norm.cc differentiates only through out).
    """
    f32 = jnp.float32

    def _stats(x):
        s = jnp.sum(x, axis=red, dtype=f32)
        s2 = jnp.sum(jnp.square(x.astype(f32)), axis=red)
        mean = s / n
        var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
        return mean, var

    @jax.custom_vjp
    def f(x, gamma, beta):
        mean, var = _stats(x)
        inv_std = lax.rsqrt(var + eps)
        g32 = jnp.ones_like(inv_std) if fix_gamma else gamma.astype(f32)
        scale = g32 * inv_std
        shift = beta.astype(f32) - mean * scale
        out = (x.astype(f32) * scale.reshape(bshape)
               + shift.reshape(bshape)).astype(x.dtype)
        return out, mean, var

    def f_fwd(x, gamma, beta):
        mean, var = _stats(x)
        inv_std = lax.rsqrt(var + eps)
        g32 = jnp.ones_like(inv_std) if fix_gamma else gamma.astype(f32)
        scale = g32 * inv_std
        shift = beta.astype(f32) - mean * scale
        out = (x.astype(f32) * scale.reshape(bshape)
               + shift.reshape(bshape)).astype(x.dtype)
        return (out, mean, var), (x, gamma, mean, inv_std, g32)

    def f_bwd(res, cts):
        x, gamma, mean, inv_std, g32 = res
        dy = cts[0]                     # cotangents of mean/var dropped
        t1 = jnp.sum(dy, axis=red, dtype=f32)
        t2 = jnp.sum(dy.astype(f32) * x.astype(f32), axis=red)
        dgamma = (t2 - mean * t1) * inv_std
        dbeta = t1
        # dx = scale*(dy - dbeta/n - xhat*dgamma/n) expanded to a single
        # a*dy + b*x + c per-channel affine pass
        scale = g32 * inv_std
        bcoef = -scale * inv_std * dgamma / n
        ccoef = (scale * inv_std * dgamma * mean - scale * dbeta) / n
        dx = (dy.astype(f32) * scale.reshape(bshape)
              + x.astype(f32) * bcoef.reshape(bshape)
              + ccoef.reshape(bshape)).astype(x.dtype)
        if fix_gamma:
            dgamma = jnp.zeros_like(dgamma)
        return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)

    f.defvjp(f_fwd, f_bwd)
    return f


@register("BatchNorm", aliases=("batch_norm", "CuDNNBatchNorm"), num_outputs=5,
          num_visible_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
          mutate_inputs=(("moving_mean", 3), ("moving_var", 4)))
def batch_norm(data, gamma, beta, moving_mean=None, moving_var=None, *,
               eps=1e-3, momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False):
    """Batch normalization (ref src/operator/nn/batch_norm.cc).
    Returns (out, save_mean, save_inv_var, new_moving_mean, new_moving_var);
    the last two update the aux states (reference mutates them in place).
    Training mode runs the fused one-pass schedule (_bn_train_fused)."""
    ctx = current_op_context()
    ax = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[i] if i == ax else 1 for i in range(data.ndim))

    if moving_mean is None:
        moving_mean = jnp.zeros(data.shape[ax], dtype=jnp.float32)
    if moving_var is None:
        moving_var = jnp.ones(data.shape[ax], dtype=jnp.float32)

    use_batch_stats = ctx.is_train and not use_global_stats
    if use_batch_stats:
        n = 1
        for i in red:
            n *= data.shape[i]
        out, mean, var = _bn_train_fused(red, bshape, float(eps),
                                         bool(fix_gamma), float(n))(
            data, gamma, beta)
        inv_std = lax.rsqrt(var + eps)
        # keep the aux dtype: fp32 math, cast back so the moving stats
        # never drift dtype step-over-step (which would silently retrace
        # the jitted step after the first update)
        new_mm = (moving_mean.astype(jnp.float32) * momentum
                  + mean * (1 - momentum)).astype(moving_mean.dtype)
        new_mv = (moving_var.astype(jnp.float32) * momentum
                  + var * (1 - momentum)).astype(moving_var.dtype)
    else:
        mean = lax.stop_gradient(moving_mean.astype(jnp.float32))
        var = lax.stop_gradient(moving_var.astype(jnp.float32))
        new_mm, new_mv = moving_mean, moving_var
        inv_std = lax.rsqrt(var + eps)
        g32 = (jnp.ones_like(inv_std) if fix_gamma
               else gamma.astype(jnp.float32))
        scale = g32 * inv_std
        shift = beta.astype(jnp.float32) - mean * scale
        out = (data.astype(jnp.float32) * scale.reshape(bshape)
               + shift.reshape(bshape)).astype(data.dtype)
    return (out, mean, inv_std,
            lax.stop_gradient(new_mm), lax.stop_gradient(new_mv))


def _use_layernorm_kernel(axis_last):
    """Select the fused Pallas LayerNorm kernel.  MXNET_LN_IMPL:
    ``auto`` (default) = the fused kernel on TPU when normalizing the
    last axis, ``xla`` = the reference chain, ``pallas`` = require the
    kernel (interpret mode off-TPU — the tier-1 parity convention).
    Semantics shared with the other kernel knobs via
    ``pallas.dispatch.choose_impl`` (docs/KERNELS.md)."""
    from ..pallas.dispatch import use_layernorm_pallas
    return use_layernorm_pallas(axis_last)


@register("LayerNorm", aliases=("layer_norm",), num_outputs=3,
          num_visible_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1)
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    """Layer normalization (ref src/operator/nn/layer_norm.cc).

    The transformer symbol path (axis=-1, stats outputs hidden) routes
    through the fused Pallas forward/backward kernel when selected by
    ``MXNET_LN_IMPL`` — one VMEM pass instead of XLA's separate
    mean/var/normalize/scale chains; the kernel's custom VJP does not
    propagate mean/inv_std cotangents, so routing requires
    ``output_mean_var=False`` (where they are structurally unused)."""
    ax = int(axis) % data.ndim
    if (not output_mean_var and data.ndim >= 2
            and _use_layernorm_kernel(ax == data.ndim - 1)):
        from ..pallas import layernorm_fused
        out, mean, inv_std = layernorm_fused(
            data, gamma.reshape(-1), beta.reshape(-1), eps=eps)
        return (out, mean, inv_std)
    xf = data.astype(jnp.float32)
    mean = jnp.mean(xf, axis=ax, keepdims=True)
    var = jnp.var(xf, axis=ax, keepdims=True)
    inv_std = lax.rsqrt(var + eps)
    out = (xf - mean) * inv_std
    bshape = tuple(data.shape[i] if i == ax else 1 for i in range(data.ndim))
    out = out * gamma.reshape(bshape) + beta.reshape(bshape)
    return (out.astype(data.dtype), jnp.squeeze(mean, ax), jnp.squeeze(inv_std, ax))


@register("InstanceNorm")
def instance_norm(data, gamma, beta, *, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
    elif mode == "channel":
        red = (1,)
    else:  # spatial
        red = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / norm


@register("LRN")
def lrn(data, *, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response norm across channels (ref src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = int(nsize) // 2
    summed = lax.reduce_window(
        sq, 0.0, lax.add, (1, int(nsize), 1, 1), (1, 1, 1, 1),
        [(0, 0), (half, half), (0, 0), (0, 0)])
    return data * jnp.power(knorm + alpha * summed / nsize, -beta)


# ----------------------------------------------------------------------
# Pooling
# ----------------------------------------------------------------------
@register("Pooling", aliases=("pooling",))
def pooling(data, *, kernel=(), pool_type="max", global_pool=False, stride=(),
            pad=(), pooling_convention="valid", cudnn_off=False,
            count_include_pad=True, p_value=2, layout=None):
    """Max/avg/sum/lp pooling (ref src/operator/nn/pooling.cc).
    ``layout`` accepts channel-last strings (NWC/NHWC/NDHWC) so pooling
    composes with NHWC convolutions without relayouts."""
    nd = data.ndim - 2
    chlast = bool(layout) and str(layout).endswith("C")
    sp0 = 1 if chlast else 2            # first spatial axis
    if global_pool:
        red = tuple(range(sp0, sp0 + nd))
        if pool_type == "max":
            out = jnp.max(data, axis=red, keepdims=True)
        elif pool_type == "sum":
            out = jnp.sum(data, axis=red, keepdims=True)
        else:
            out = jnp.mean(data, axis=red, keepdims=True)
        return out
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd) if stride else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    if chlast:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        base_pad = [(0, 0)] + [(p, p) for p in pad] + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        base_pad = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pooling_convention == "full":
        # ceil semantics: add extra right-pad so the last window fits
        for i in range(nd):
            size = data.shape[sp0 + i] + 2 * pad[i]
            out_sz = -(-(size - kernel[i]) // stride[i]) + 1  # ceil
            need = (out_sz - 1) * stride[i] + kernel[i] - size
            base_pad[sp0 + i] = (pad[i], pad[i] + max(0, need))
    if pool_type == "max":
        if _use_argmax_maxpool(data.dtype):
            return _maxpool_argmax_vjp(data, window, strides,
                                       tuple(map(tuple, base_pad)))
        init = (-jnp.inf if jnp.issubdtype(data.dtype, jnp.floating)
                else jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max, window, strides, base_pad)
    summed = lax.reduce_window(data, 0.0, lax.add, window, strides, base_pad)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, jnp.asarray(0, data.dtype), lax.add,
                                   window, strides, base_pad)
        return summed / counts
    raise ValueError("unsupported pool_type %s" % pool_type)



# ----------------------------------------------------------------------
# Max-pool with an elementwise backward.
#
# XLA differentiates reduce_window(max) into select_and_scatter, which
# the r4 roofline measured at 540 GB/s (1.7 ms/step in ResNet-50 —
# docs/ROOFLINE.md). The custom VJP below recomputes the argmax in
# backward from shifted strided slices and scatters with dilating pads.
# Tie-break matches select_and_scatter (first window position in
# row-major order wins).
#
# MEASURED NEGATIVE (r5, docs/PERF.md): on the v5e ResNet-50 step this
# formulation is ~23 ms SLOWER than select_and_scatter — XLA does not
# fuse the 9 interior-dilated pads into one accumulation; each `placed`
# array materialises at full padded size (~420 MB x 9 at batch 256).
# Default is therefore the XLA path; the VJP stays selectable
# (MXNET_MAXPOOL_VJP=argmax) as the reproducible experiment.
# ----------------------------------------------------------------------
def _use_argmax_maxpool(dtype):
    import os
    impl = os.environ.get("MXNET_MAXPOOL_VJP", "xla")
    if impl == "xla":
        return False
    if impl != "argmax":
        raise ValueError(f"MXNET_MAXPOOL_VJP={impl}; use argmax|xla")
    return jnp.issubdtype(dtype, jnp.floating)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool_argmax_vjp(data, window, strides, pads):
    init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
        jnp.iinfo(data.dtype).min
    return lax.reduce_window(data, init, lax.max, window, strides,
                             list(pads))


def _maxpool_fwd(data, window, strides, pads):
    y = _maxpool_argmax_vjp(data, window, strides, pads)
    return y, (data, y)


def _window_offsets(window):
    import itertools
    return itertools.product(*(range(k) for k in window))


def _maxpool_bwd(window, strides, pads, residual, dy):
    x, y = residual
    neg = jnp.asarray(-jnp.inf, x.dtype)
    x_padded = jnp.pad(x, list(pads), constant_values=neg) \
        if any(lo or hi for lo, hi in pads) else x
    padded_shape = x_padded.shape

    taken = None
    dx_padded = None
    for offs in _window_offsets(window):
        # the window element at `offs` across every output position
        limits = [o + (ys - 1) * st + 1
                  for o, ys, st in zip(offs, y.shape, strides)]
        xk = lax.slice(x_padded, list(offs), limits, list(strides))
        match = xk == y
        if taken is None:
            first = match
            taken = match
        else:
            first = match & ~taken
            taken = taken | match
        gk = jnp.where(first, dy, jnp.zeros_like(dy))
        # scatter back: dilate by stride, shift by the offset
        cfg = [(int(o), int(ps - (o + (ys - 1) * st + 1)), int(st - 1))
               for o, ps, ys, st in
               zip(offs, padded_shape, y.shape, strides)]
        placed = lax.pad(gk, jnp.asarray(0, gk.dtype), cfg)
        dx_padded = placed if dx_padded is None else dx_padded + placed
    if any(lo or hi for lo, hi in pads):
        starts = [lo for lo, _ in pads]
        limits = [lo + n for (lo, _), n in zip(pads, x.shape)]
        dx = lax.slice(dx_padded, starts, limits)
    else:
        dx = dx_padded
    return (dx,)


_maxpool_argmax_vjp.defvjp(_maxpool_fwd, _maxpool_bwd)


@register("UpSampling", key_var_num_args="num_args")
def upsampling(*args, scale=2, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    """Nearest/bilinear upsampling (ref src/operator/upsampling.cc)."""
    data = args[0]
    s = int(scale)
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, s, axis=2), s, axis=3)
        return out
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * s, w * s), method="bilinear")


# ----------------------------------------------------------------------
# Activations
# ----------------------------------------------------------------------
@register("Activation", aliases=("activation",))
def activation(data, *, act_type):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %s" % act_type)


@register("LeakyReLU")
@needs_rng
def leaky_relu(data, gamma=None, *, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    """leaky/prelu/elu/selu/gelu/rrelu (ref src/operator/leaky_relu.cc)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "gelu_tanh":
        # tanh-approximated GELU (GPT-2 convention) — extension beyond the
        # reference's erf GELU; polynomial VPU math, no erf transcendental
        return jax.nn.gelu(data, approximate=True)
    if act_type == "rrelu":
        ctx = current_op_context()
        if ctx.is_train:
            key = ctx.next_rng_key()
            slope_s = jax.random.uniform(key, data.shape, dtype=data.dtype,
                                         minval=lower_bound, maxval=upper_bound)
        else:
            slope_s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, slope_s * data)
    raise ValueError("unknown act_type %s" % act_type)


@register("softmax_cross_entropy", aliases=("SoftmaxCrossEntropy",))
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    oh = jax.nn.one_hot(label.astype("int32"), data.shape[-1], dtype=logp.dtype)
    return -jnp.sum(oh * logp)


# ----------------------------------------------------------------------
# Dropout
# ----------------------------------------------------------------------
@register("Dropout", aliases=("dropout",), num_outputs=2, num_visible_outputs=1)
@needs_rng
def dropout_op(data, *, p=0.5, mode="training", axes=(), cudnn_off=False):
    """Dropout (ref src/operator/nn/dropout-inl.h). mask is the 2nd output."""
    ctx = current_op_context()
    if (not ctx.is_train and mode != "always") or p <= 0.0:
        return data, jnp.ones_like(data)
    key = ctx.next_rng_key()
    shape = data.shape
    if axes:
        shape = tuple(1 if i in tuple(axes) else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask, jnp.broadcast_to(mask, data.shape)


# ----------------------------------------------------------------------
# SoftmaxOutput — custom gradient identical to the reference's fused
# softmax + cross-entropy backward (src/operator/softmax_output-inl.h).
# ----------------------------------------------------------------------
def _softmax_fwd(data, label, attrs):
    if attrs["multi_output"]:
        # data (n, c, d1...): softmax over axis 1
        prob = jax.nn.softmax(data, axis=1)
    else:
        prob = jax.nn.softmax(data, axis=-1)
    return prob


def _softmax_grad(prob, label, attrs):
    grad_scale = attrs["grad_scale"]
    ignore_label = attrs["ignore_label"]
    use_ignore = attrs["use_ignore"]
    normalization = attrs["normalization"]
    smooth_alpha = attrs["smooth_alpha"]
    if attrs["multi_output"]:
        caxis, nclass = 1, prob.shape[1]
        lab = label.astype("int32")
        oh = jnp.moveaxis(jax.nn.one_hot(lab, nclass, dtype=prob.dtype), -1, 1)
    else:
        caxis, nclass = prob.ndim - 1, prob.shape[-1]
        lab = label.astype("int32")
        oh = jax.nn.one_hot(lab, nclass, dtype=prob.dtype)
    if smooth_alpha:
        oh = oh * (1.0 - smooth_alpha) + smooth_alpha / (nclass - 1) * (1.0 - oh)
    grad = prob - oh
    valid = jnp.ones(lab.shape, dtype=prob.dtype)
    if use_ignore:
        valid = (lab != int(ignore_label)).astype(prob.dtype)
        grad = grad * jnp.expand_dims(valid, caxis)
    if normalization == "valid":
        grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
    elif normalization == "batch":
        grad = grad / prob.shape[0]
    return grad * grad_scale


@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, *, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    attrs = dict(grad_scale=grad_scale, ignore_label=ignore_label,
                 multi_output=multi_output, use_ignore=use_ignore,
                 normalization=normalization, smooth_alpha=smooth_alpha)

    @jax.custom_vjp
    def _f(d, l):
        return _softmax_fwd(d, l, attrs)

    def _f_fwd(d, l):
        prob = _softmax_fwd(d, l, attrs)
        return prob, (prob, l)

    def _f_bwd(res, g):
        prob, l = res
        # reference ignores upstream out_grad unless out_grad=True
        return _softmax_grad(prob, l, attrs).astype(prob.dtype), jnp.zeros_like(l)

    _f.defvjp(_f_fwd, _f_bwd)
    return _f(data, label)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, *, grad_scale=1.0):
    """Identity fwd; grad = (pred - label)/batch (ref src/operator/regression_output-inl.h)."""
    @jax.custom_vjp
    def _f(d, l):
        return d

    def _fwd(d, l):
        return d, (d, l)

    def _bwd(res, g):
        d, l = res
        grad = (d - l.reshape(d.shape)) * grad_scale / d.shape[0]
        return grad, jnp.zeros_like(l)

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


@register("MAERegressionOutput")
def mae_regression_output(data, label, *, grad_scale=1.0):
    @jax.custom_vjp
    def _f(d, l):
        return d

    def _fwd(d, l):
        return d, (d, l)

    def _bwd(res, g):
        d, l = res
        grad = jnp.sign(d - l.reshape(d.shape)) * grad_scale / d.shape[0]
        return grad, jnp.zeros_like(l)

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, *, grad_scale=1.0):
    @jax.custom_vjp
    def _f(d, l):
        return jax.nn.sigmoid(d)

    def _fwd(d, l):
        out = jax.nn.sigmoid(d)
        return out, (out, l)

    def _bwd(res, g):
        out, l = res
        grad = (out - l.reshape(out.shape)) * grad_scale / out.shape[0]
        return grad, jnp.zeros_like(l)

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# ----------------------------------------------------------------------
# Attention (new TPU-native capability — the reference predates
# attention entirely, SURVEY.md §5.7; sequence-parallel forms live in
# parallel/ring_attention.py)
# ----------------------------------------------------------------------
def _use_flash_attention(seq_len, head_dim, dtype):
    """Select the fused Pallas flash kernel.  MXNET_ATTN_IMPL:
    ``auto`` (default) = flash when the backend/geometry supports it,
    ``xla`` = force the materialized-softmax path (A/B runs),
    ``flash`` = require the kernel — raise instead of silently measuring
    the wrong path when it cannot run.  The selection semantics live in
    ``pallas.dispatch.choose_impl``, shared with the paged-attention
    and quantize knobs so the three contracts cannot drift."""
    import os
    from ..pallas.dispatch import choose_impl
    supported = (jax.default_backend() == "tpu" and head_dim % 128 == 0
                 and seq_len % 512 == 0
                 and dtype in (jnp.bfloat16, jnp.float32))
    return choose_impl(
        "MXNET_ATTN_IMPL", os.environ.get("MXNET_ATTN_IMPL", "auto"),
        "flash", supported,
        why=f"backend={jax.default_backend()}, head_dim={head_dim}, "
            f"seq={seq_len}, dtype={dtype}; need TPU, head_dim%128==0, "
            f"seq%512==0, bf16/f32",
        fallback_reason="flash-geometry")


def _flash_attention(q, k, v, sm_scale):
    """Invoke the Pallas flash kernel on head-major (B, H, S, D) inputs
    with the 512x512 block geometry measured fastest on v5e at S1024/D128
    (docs/PERF.md round 5 — the library defaults measure SLOWER than the
    XLA path)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes as _BlockSizes, flash_attention as _flash)
    blk = 512  # geometry gate guarantees S % 512 == 0
    bs = _BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk,
        block_k_dkv=blk, block_q_dkv=blk,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
    return _flash(q, k, v, causal=True, sm_scale=sm_scale, block_sizes=bs)


@register("_contrib_CausalSelfAttention", aliases=("CausalSelfAttention",))
def causal_self_attention(qkv, *, num_heads, scale=None):
    """Fused causal multi-head self-attention over a packed QKV tensor:
    (B, S, 3*d_model) -> (B, S, d_model).

    TPU-first schedule: QK^T and PV are two MXU einsums (bf16 inputs,
    fp32 accumulation on the MXU); softmax statistics run in fp32 inside
    the fusion; the whole op is rematerialized in backward
    (``jax.checkpoint``) so no (S, S) attention matrix is ever saved as
    a residual — live memory stays O(S·d) per layer.
    """
    B, S, d3 = qkv.shape
    d = d3 // 3
    H = int(num_heads)
    if d % H:
        raise ValueError("d_model %d not divisible by num_heads %d" % (d, H))
    D = d // H
    sc = (1.0 / D ** 0.5) if scale is None else float(scale)

    if _use_flash_attention(S, D, qkv.dtype):
        # Pallas flash kernel: QK^T -> online softmax -> PV in ONE kernel,
        # blocks resident in VMEM — the (S, S) score tensor never touches
        # HBM in forward OR backward (the kernel brings its own
        # recomputing VJP, so no jax.checkpoint wrapper here; wrapping
        # would re-pay the whole kernel a third time).
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        o = _flash_attention(to_heads(q), to_heads(k), to_heads(v), sc)
        return o.transpose(0, 2, 1, 3).reshape(B, S, d)

    @jax.checkpoint
    def attn(qkv):
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        k = k.reshape(B, S, H, D)
        v = v.reshape(B, S, H, D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sc
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(qkv.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return o.reshape(B, S, d)

    return attn(qkv)


@register("_contrib_FusedCausalSelfAttention",
          aliases=("FusedCausalSelfAttention",))
def fused_causal_self_attention(data, qkv_weight, qkv_bias, proj_weight,
                                proj_bias, *, num_heads, scale=None,
                                head_axis=None):
    """Whole attention sublayer in one op: QKV projection -> causal MHA ->
    output projection, (B, S, d) -> (B, S, d).

    TPU-first layout trick: the projections are dot_generals that emit /
    consume the HEAD-MAJOR (B, H, S, D) layout directly, so no transpose
    ever materialises between the matmuls and the fused Pallas flash
    kernel (a separate (B,S,H,D)->(B,H,S,D) copy costs ~0.5 ms/layer
    fwd+bwd at d2048/S1024 on v5e — measured in docs/PERF.md).  Weight
    layouts match the reference FullyConnected convention ((3d, d) /
    (d, d) row-major), so checkpoints from the unfused pair load
    unchanged.

    ``head_axis`` (docs/SHARDING.md): a mesh-axis name partitioning the
    HEAD dim for tensor parallelism — q/k/v/o get GSPMD sharding
    constraints over (None, head_axis) so each mp shard computes its own
    heads locally (the Megatron split).  Inert when no mesh is selected
    or the selected mesh lacks the axis; programs are cached per mesh
    fingerprint so the trace-time mesh read cannot go stale.
    """
    B, S, d = data.shape
    H = int(num_heads)
    if d % H:
        raise ValueError("d_model %d not divisible by num_heads %d" % (d, H))
    D = d // H
    sc = (1.0 / D ** 0.5) if scale is None else float(scale)

    _shard_heads = lambda t: t
    if head_axis is not None:
        from .. import sharding as _sharding
        _mesh = _sharding.get_mesh()
        if _mesh is not None and str(head_axis) in _mesh.axis_names:
            from jax.sharding import NamedSharding, PartitionSpec as P
            _ns = NamedSharding(_mesh, P(None, str(head_axis)))
            _shard_heads = lambda t: jax.lax.with_sharding_constraint(t, _ns)

    Wqkv = qkv_weight.reshape(3, H, D, d)
    bqkv = qkv_bias.reshape(3, H, 1, D)
    q = _shard_heads(jnp.einsum("bsd,hed->bhse", data, Wqkv[0]) + bqkv[0])
    k = _shard_heads(jnp.einsum("bsd,hed->bhse", data, Wqkv[1]) + bqkv[1])
    v = _shard_heads(jnp.einsum("bsd,hed->bhse", data, Wqkv[2]) + bqkv[2])

    if _use_flash_attention(S, D, data.dtype):
        o = _flash_attention(q, k, v, sc)
    else:
        @jax.checkpoint
        def attn(q, k, v):
            s = jnp.einsum("bhqe,bhke->bhqk", q, k) * sc
            mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
            s = jnp.where(mask, s.astype(jnp.float32), -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bhke->bhqe", p, v)
        o = attn(q, k, v)

    o = _shard_heads(o)
    return jnp.einsum("bhse,dhe->bsd", o,
                      proj_weight.reshape(d, H, D)) + proj_bias


# ----------------------------------------------------------------------
# Paged-KV-cache attention (mx.decode — docs/DECODE.md)
#
# The generative-serving pair of FusedCausalSelfAttention: the KV cache
# lives in fixed-size device blocks ((num_blocks, block_size, H, D) per
# layer) and each sequence addresses it through a runtime block table —
# PagedAttention (vLLM, SOSP '23) expressed as XLA gather/scatter so
# one compiled program serves every ragged batch of sequences with
# zero retraces.  Block tables / positions / lengths are ARRAY inputs,
# never static attrs, so nothing about sequence state is baked into
# the trace.  Out-of-range scatter indices (padded slots, positions
# past a prompt) use ``num_blocks*block_size`` — one past the end —
# with mode='drop': negative sentinels would WRAP to the last cache
# row and corrupt a live block.
# ----------------------------------------------------------------------
def _paged_qkv_weights(qkv_weight, qkv_bias, d, H, D):
    """Reference FullyConnected layout ((3d, d) packed rows) viewed
    head-major so checkpoints from the training graph load unchanged."""
    return qkv_weight.reshape(3, H, D, d), qkv_bias.reshape(3, H, D)


@register("_contrib_PagedDecodeAttention",
          aliases=("PagedDecodeAttention",), num_outputs=3)
def paged_decode_attention(data, qkv_weight, qkv_bias, proj_weight,
                           proj_bias, k_cache, v_cache, block_table,
                           positions, *, num_heads, scale=None):
    """One autoregressive decode step over a paged KV cache.

    data (C, 1, d): current-token hidden states for C fixed batch
    slots; k_cache/v_cache (num_blocks, block_size, H, D); block_table
    (C, M) block ids per slot; positions (C, 1) the 0-based position of
    the current token (< 0 marks an inactive/padded slot — its write is
    dropped and its output is garbage the engine masks).  Outputs
    (attn_out (C, 1, d), new_k_cache, new_v_cache): the current token's
    K/V are scattered into the cache first, then attention runs over
    the gathered context 0..position.  Weight names/layouts match
    FusedCausalSelfAttention, so the training checkpoint serves decode
    with no conversion."""
    C, _, d = data.shape
    H = int(num_heads)
    if d % H:
        raise ValueError("d_model %d not divisible by num_heads %d" % (d, H))
    D = d // H
    sc = (1.0 / D ** 0.5) if scale is None else float(scale)

    x = data.reshape(C, d)
    Wqkv, bqkv = _paged_qkv_weights(qkv_weight, qkv_bias, d, H, D)
    q = jnp.einsum("cd,hed->che", x, Wqkv[0]) + bqkv[0]
    k = jnp.einsum("cd,hed->che", x, Wqkv[1]) + bqkv[1]
    v = jnp.einsum("cd,hed->che", x, Wqkv[2]) + bqkv[2]

    nb, bs = k_cache.shape[0], k_cache.shape[1]
    kf = k_cache.reshape(nb * bs, H, D)
    vf = v_cache.reshape(nb * bs, H, D)
    pos = positions.reshape(C).astype(jnp.int32)
    table = block_table.astype(jnp.int32)              # (C, M)
    M = table.shape[1]

    # scatter this token's K/V: flat row = table[pos // bs] * bs + pos % bs
    blk = jnp.clip(pos // bs, 0, M - 1)
    row_blk = jnp.take_along_axis(table, blk[:, None], axis=1)[:, 0]
    widx = jnp.where(pos >= 0, row_blk * bs + pos % bs, nb * bs)
    kf = kf.at[widx].set(k.astype(kf.dtype), mode="drop")
    vf = vf.at[widx].set(v.astype(vf.dtype), mode="drop")

    from ..pallas import paged_decode_attend, use_paged_pallas
    if use_paged_pallas():
        # Pallas kernel (docs/KERNELS.md): walks the block table inside
        # the kernel — one (bs, H, D) K/V block in VMEM at a time with
        # an online softmax, so the (C, M*bs, H, D) gathered-context
        # temp of the XLA path below never exists.  Inactive slots
        # (pos < 0) come back as exact zeros instead of the XLA path's
        # masked garbage; the engine masks both.
        o = paged_decode_attend(q, kf.reshape(k_cache.shape),
                                vf.reshape(v_cache.shape), table, pos,
                                scale=sc)
    else:
        # gather the whole addressable context per slot and mask
        # causally; padded table entries read block 0 but sit behind
        # the mask
        ctx = M * bs
        j = jnp.arange(ctx)
        ridx = table[:, j // bs] * bs + (j % bs)       # (C, ctx)
        kctx = jnp.take(kf, ridx, axis=0, mode="clip")  # (C, ctx, H, D)
        vctx = jnp.take(vf, ridx, axis=0, mode="clip")
        s = jnp.einsum("che,cjhe->chj", q, kctx) * sc
        mask = j[None, None, :] <= jnp.maximum(pos, 0)[:, None, None]
        s = jnp.where(mask, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o = jnp.einsum("chj,cjhe->che", p, vctx)
    out = jnp.einsum("che,dhe->cd", o,
                     proj_weight.reshape(d, H, D)) + proj_bias
    return (out.reshape(C, 1, d), kf.reshape(k_cache.shape),
            vf.reshape(v_cache.shape))


@register("_contrib_PagedPrefillAttention",
          aliases=("PagedPrefillAttention",), num_outputs=3)
def paged_prefill_attention(data, qkv_weight, qkv_bias, proj_weight,
                            proj_bias, k_cache, v_cache, block_table,
                            lengths, *, num_heads, scale=None):
    """Prompt-phase attention that also populates the paged KV cache.

    data (B, S, d) is the padded prompt batch; lengths (B,) the real
    prompt lengths; block_table (B, M) the destination blocks.  The
    attention itself is the same head-major causal MHA as
    FusedCausalSelfAttention (flash kernel when the TPU geometry
    allows, fp32-softmax XLA path otherwise); additionally K/V rows for
    positions < length are scattered into the cache so decode can
    continue the sequence.  Outputs (hidden (B, S, d), new_k_cache,
    new_v_cache)."""
    B, S, d = data.shape
    H = int(num_heads)
    if d % H:
        raise ValueError("d_model %d not divisible by num_heads %d" % (d, H))
    D = d // H
    sc = (1.0 / D ** 0.5) if scale is None else float(scale)

    from ..pallas import paged_prefill_attend, use_paged_pallas
    if use_paged_pallas():
        # Pallas kernel (docs/KERNELS.md): causal attention per query
        # block with the cache scatter FUSED into the same kernel —
        # K/V rows land in their table-addressed cache blocks as they
        # are produced, so the separate (B*S)-row XLA scatter below
        # (and its index math) never runs.  Projections emit/consume
        # the kernel's seq-major (B, S, H, D) layout directly.
        Wqkv, bqkv = _paged_qkv_weights(qkv_weight, qkv_bias, d, H, D)
        q = jnp.einsum("bsd,hed->bshe", data, Wqkv[0]) + bqkv[0]
        k = jnp.einsum("bsd,hed->bshe", data, Wqkv[1]) + bqkv[1]
        v = jnp.einsum("bsd,hed->bshe", data, Wqkv[2]) + bqkv[2]
        o, kc, vc = paged_prefill_attend(
            q, k, v, k_cache, v_cache, block_table.astype(jnp.int32),
            lengths.reshape(B).astype(jnp.int32), scale=sc)
        out = jnp.einsum("bshe,dhe->bsd", o,
                         proj_weight.reshape(d, H, D)) + proj_bias
        return out, kc, vc

    Wqkv = qkv_weight.reshape(3, H, D, d)
    bqkv = qkv_bias.reshape(3, H, 1, D)
    q = jnp.einsum("bsd,hed->bhse", data, Wqkv[0]) + bqkv[0]
    k = jnp.einsum("bsd,hed->bhse", data, Wqkv[1]) + bqkv[1]
    v = jnp.einsum("bsd,hed->bhse", data, Wqkv[2]) + bqkv[2]

    if _use_flash_attention(S, D, data.dtype):
        o = _flash_attention(q, k, v, sc)
    else:
        s = jnp.einsum("bhqe,bhke->bhqk", q, k) * sc
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o = jnp.einsum("bhqk,bhke->bhqe", p, v)
    out = jnp.einsum("bhse,dhe->bsd", o,
                     proj_weight.reshape(d, H, D)) + proj_bias

    nb, bs = k_cache.shape[0], k_cache.shape[1]
    kf = k_cache.reshape(nb * bs, H, D)
    vf = v_cache.reshape(nb * bs, H, D)
    table = block_table.astype(jnp.int32)              # (B, M)
    L = lengths.reshape(B).astype(jnp.int32)
    M = table.shape[1]
    jpos = jnp.arange(S)
    blk = jnp.clip(jpos // bs, 0, M - 1)
    base = jnp.take_along_axis(table, jnp.broadcast_to(blk[None], (B, S)),
                               axis=1)
    widx = jnp.where(jpos[None, :] < L[:, None],
                     base * bs + jpos % bs, nb * bs)   # OOB sentinel
    kw = k.transpose(0, 2, 1, 3).reshape(B * S, H, D)
    vw = v.transpose(0, 2, 1, 3).reshape(B * S, H, D)
    kf = kf.at[widx.reshape(B * S)].set(kw.astype(kf.dtype), mode="drop")
    vf = vf.at[widx.reshape(B * S)].set(vw.astype(vf.dtype), mode="drop")
    return out, kf.reshape(k_cache.shape), vf.reshape(v_cache.shape)


@register("_contrib_PagedChunkPrefillAttention",
          aliases=("PagedChunkPrefillAttention",), num_outputs=3)
def paged_chunk_prefill_attention(data, qkv_weight, qkv_bias,
                                  proj_weight, proj_bias, k_cache,
                                  v_cache, block_table, start, lengths,
                                  *, num_heads, scale=None):
    """Chunked prompt-phase attention over an EXISTING cache prefix.

    The chunked-prefill variant of PagedPrefillAttention (Sarathi-Serve
    /Orca-style stall-free scheduling, docs/DECODE.md): data (B, K, d)
    holds one K-token CHUNK per row whose tokens sit at absolute
    positions ``[start[b], start[b] + lengths[b])`` of the sequence;
    earlier chunks' K/V already live in the paged cache, addressed by
    ``block_table (B, M)``.  The chunk's K/V rows are scattered first,
    then every chunk query attends causally against the FULL context so
    far (prior chunks fully visible, in-chunk keys causally).  Rows
    past ``lengths[b]`` are padding (scatter dropped, output garbage
    the engine masks); ``lengths[b] == 0`` makes row b a no-op.
    Outputs (hidden (B, K, d), new_k_cache, new_v_cache).  Weight
    names/layouts match FusedCausalSelfAttention, so the training
    checkpoint serves chunked prefill with no conversion."""
    B, K, d = data.shape
    H = int(num_heads)
    if d % H:
        raise ValueError("d_model %d not divisible by num_heads %d" % (d, H))
    D = d // H
    sc = (1.0 / D ** 0.5) if scale is None else float(scale)
    st = start.reshape(B).astype(jnp.int32)
    L = lengths.reshape(B).astype(jnp.int32)
    table = block_table.astype(jnp.int32)              # (B, M)
    M = table.shape[1]
    nb, bs = k_cache.shape[0], k_cache.shape[1]

    from ..pallas import paged_chunk_prefill_attend, use_paged_pallas
    if use_paged_pallas():
        # Pallas kernel (docs/KERNELS.md): streams the context cache
        # block by block with an online softmax, merging the chunk's
        # own K/V into each block in-kernel and writing it back through
        # the aliased caches — the (B, M*bs, H, D) gathered-context
        # temp of the XLA path below never exists.
        Wqkv, bqkv = _paged_qkv_weights(qkv_weight, qkv_bias, d, H, D)
        q = jnp.einsum("bsd,hed->bshe", data, Wqkv[0]) + bqkv[0]
        k = jnp.einsum("bsd,hed->bshe", data, Wqkv[1]) + bqkv[1]
        v = jnp.einsum("bsd,hed->bshe", data, Wqkv[2]) + bqkv[2]
        o, kc, vc = paged_chunk_prefill_attend(
            q, k, v, k_cache, v_cache, table, st, L, scale=sc)
        out = jnp.einsum("bshe,dhe->bsd", o,
                         proj_weight.reshape(d, H, D)) + proj_bias
        return out, kc, vc

    Wqkv = qkv_weight.reshape(3, H, D, d)
    bqkv = qkv_bias.reshape(3, H, 1, D)
    q = jnp.einsum("bsd,hed->bhse", data, Wqkv[0]) + bqkv[0]
    k = jnp.einsum("bsd,hed->bhse", data, Wqkv[1]) + bqkv[1]
    v = jnp.einsum("bsd,hed->bhse", data, Wqkv[2]) + bqkv[2]

    # scatter the chunk's rows at their ABSOLUTE positions first, so
    # the gather below reads a cache that already contains them (the
    # in-chunk causal mask does the rest)
    kf = k_cache.reshape(nb * bs, H, D)
    vf = v_cache.reshape(nb * bs, H, D)
    j = jnp.arange(K)
    apos = st[:, None] + j[None, :]                    # (B, K) absolute
    blk = jnp.clip(apos // bs, 0, M - 1)
    base = jnp.take_along_axis(table, blk, axis=1)
    widx = jnp.where(j[None, :] < L[:, None],
                     base * bs + apos % bs, nb * bs)   # OOB sentinel
    kw = k.transpose(0, 2, 1, 3).reshape(B * K, H, D)
    vw = v.transpose(0, 2, 1, 3).reshape(B * K, H, D)
    kf = kf.at[widx.reshape(B * K)].set(kw.astype(kf.dtype), mode="drop")
    vf = vf.at[widx.reshape(B * K)].set(vw.astype(vf.dtype), mode="drop")

    # gather the whole addressable context per row and mask causally
    # against absolute positions; padded table entries read block 0 but
    # sit behind the mask
    ctx = M * bs
    jk = jnp.arange(ctx)
    ridx = table[:, jk // bs] * bs + (jk % bs)         # (B, ctx)
    kctx = jnp.take(kf, ridx, axis=0, mode="clip")     # (B, ctx, H, D)
    vctx = jnp.take(vf, ridx, axis=0, mode="clip")
    s = jnp.einsum("bhqe,bjhe->bhqj", q, kctx) * sc
    mask = jk[None, :] <= apos[:, :, None]             # (B, K, ctx)
    s = jnp.where(mask[:, None], s.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    o = jnp.einsum("bhqj,bjhe->bhqe", p, vctx)
    out = jnp.einsum("bhse,dhe->bsd", o,
                     proj_weight.reshape(d, H, D)) + proj_bias
    return out, kf.reshape(k_cache.shape), vf.reshape(v_cache.shape)


@register("_contrib_GatherTimestep", aliases=("GatherTimestep",))
def gather_timestep(data, index):
    """data (B, S, d), index (B,) or (B, 1) -> (B, d): data[b, index[b]]
    with the index clipped into [0, S).  Used by the prefill graph to
    read the last REAL token's hidden state (index = length - 1) so the
    lm_head matmul runs on one row, not the whole padded sequence."""
    B, S = data.shape[0], data.shape[1]
    idx = jnp.clip(index.reshape(B).astype(jnp.int32), 0, S - 1)
    idx3 = jnp.broadcast_to(idx[:, None, None], (B, 1, data.shape[2]))
    return jnp.take_along_axis(data, idx3, axis=1)[:, 0]


@register("_contrib_SwitchMoE", aliases=("SwitchMoE",), num_outputs=2,
          num_visible_outputs=2)
def switch_moe_op(data, router_weight, expert_up_weight, expert_up_bias,
                  expert_down_weight, expert_down_bias, *, num_experts,
                  num_hidden, k=1, capacity_factor=1.25):
    """Switch/top-k Mixture-of-Experts FFN as a graph operator (new
    TPU-native capability — the reference predates MoE, SURVEY.md
    §2.3). data (..., d_model) routes per-token to ``num_experts``
    expert FFNs (router_weight (d, E); expert-stacked up (E, d, h) /
    down (E, h, d) weights with (E, h) / (E, d) biases — the _weight/
    _bias name suffixes keep the framework's init and weight-decay
    conventions). Outputs: (y, aux_loss) — aux_loss is the Switch
    load-balancing loss, typically wired through ``MakeLoss`` with a
    small coefficient. Expert parallelism: shard the leading E axis of
    the expert-stacked params over an ``ep`` mesh axis (TrainStep
    tp_rule or parallel.moe.switch_moe directly). NOTE: default Xavier
    init misreads the 3-D expert stacks' fans (it treats trailing dims
    as conv extents); the transformer builder attaches per-variable
    Normal inits sized to the per-expert fan."""
    from ..parallel.moe import switch_moe as _switch

    d = data.shape[-1]
    tokens = data.reshape(-1, d)
    params = {"router": router_weight, "w1": expert_up_weight,
              "b1": expert_up_bias, "w2": expert_down_weight,
              "b2": expert_down_bias}
    y, aux = _switch(params, tokens, k=int(k),
                     capacity_factor=float(capacity_factor))
    return y.reshape(data.shape), aux


# ----------------------------------------------------------------------
# Embedding
# ----------------------------------------------------------------------
@register("Embedding")
def embedding(data, weight, *, input_dim, output_dim, dtype="float32",
              sparse_grad=False):
    """Row gather (ref src/operator/tensor/indexing_op.cc Embedding).
    TPU: lowers to a gather HLO; one-hot matmul would also hit the MXU but
    gather wins at vocab scale. ``sparse_grad=True`` is accepted for API
    parity; inside a compiled graph the weight gradient is a dense
    scatter-add (XLA's own efficient form) — to get a row_sparse gradient
    for lazy optimizer updates, use ``nd.sparse.cast_storage(grad,
    'row_sparse')`` or Parameter(grad_stype='row_sparse') in gluon."""
    idx = data.astype("int32")
    return jnp.take(weight, idx, axis=0, mode="clip")


@register("_contrib_ShardedEmbedding")
def sharded_embedding(data, weight, *, input_dim, output_dim,
                      dtype="float32", sparse_grad=True):
    """Symbol twin of embedding.ShardedEmbedding: the same gather, but
    out-of-range ids yield zero rows via the sentinel fill instead of
    Embedding's clamp — ids >= input_dim must not silently train row
    input_dim-1. Row sharding follows the WEIGHT's placement: a
    concrete table already placed on the local mesh (place_table) keeps
    its row sharding re-asserted here; inside an executor trace the
    graph's bind-device commitment governs (the executor is a
    single-device program — forcing the mesh onto its dev0-committed
    args would not compile), and GSPMD propagates any argument sharding
    on mesh-compiled callers."""
    from ..embedding import sharding as _esh
    mesh = _esh.local_mesh()
    if (mesh is not None and weight.shape[0] % mesh.devices.size == 0
            and not isinstance(weight, jax.core.Tracer)
            and isinstance(weight, jax.Array)
            and len(weight.sharding.device_set) > 1):
        weight = jax.lax.with_sharding_constraint(
            weight, _esh.table_sharding(mesh))
    idx = data.astype("int32")
    oob = jnp.logical_or(idx < 0, idx >= int(input_dim))
    idx = jnp.where(oob, int(input_dim), idx)
    return jnp.take(weight, idx, axis=0, mode="fill", fill_value=0)


@register("Correlation")
def correlation(data1, data2, *, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (ref src/operator/correlation-inl.h):
    out[b, (dy,dx), y, x] = mean over the k×k×C patch of
    data1(center) · data2(center + (dy,dx)·stride2). The CUDA kernel's
    per-displacement loop becomes one static Python loop over the
    (2r+1)² displacements, each a shifted elementwise product + box-sum
    (reduce_window) that XLA fuses; gradients ride autodiff."""
    import numpy as _np
    B, C, H, W = data1.shape
    k = int(kernel_size)
    kr = (k - 1) // 2
    d = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    pad = int(pad_size)
    ngr = d // s2                     # neighborhood grid radius
    gw = 2 * ngr + 1
    border = d + kr
    ph, pw = H + 2 * pad, W + 2 * pad
    top_h = max(int(_np.ceil((ph - 2 * border) / s1)), 1)
    top_w = max(int(_np.ceil((pw - 2 * border) / s1)), 1)
    sumelems = k * k * C

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    # extra max_displacement halo on data2 so every shift is a slice
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad + d, pad + d),
                         (pad + d, pad + d)))
    outs = []
    for dy in range(-ngr, ngr + 1):
        for dx in range(-ngr, ngr + 1):
            oy, ox = dy * s2, dx * s2
            p2s = lax.dynamic_slice(
                p2, (0, 0, d + oy, d + ox), (B, C, ph, pw))
            prod = (p1 * p2s) if is_multiply else jnp.abs(p1 - p2s)
            csum = prod.sum(axis=1)               # (B, ph, pw)
            patch = lax.reduce_window(
                csum, 0.0, lax.add, (1, k, k), (1, 1, 1),
                "VALID")                          # (B, ph-k+1, pw-k+1)
            # center (y,x) of output cell o: y = o*s1 + border; its
            # k×k window starts at y-kr -> patch index o*s1 + d
            patch = jnp.pad(patch, ((0, 0), (0, s1), (0, s1)))
            outs.append(patch[:, d:d + top_h * s1:s1,
                              d:d + top_w * s1:s1])
    out = jnp.stack(outs, axis=1)                 # (B, gw*gw, th, tw)
    return (out / sumelems).astype(data1.dtype)


@register("BilinearSampler")
def bilinear_sampler(data, grid):
    """Bilinear sampling (ref src/operator/bilinear_sampler.cc). grid in
    [-1,1] with shape (n, 2, h, w)."""
    n, c, hin, win = data.shape
    gx = (grid[:, 0] + 1.0) * (win - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (hin - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        yi = jnp.clip(yi.astype("int32"), 0, hin - 1)
        xi = jnp.clip(xi.astype("int32"), 0, win - 1)
        bidx = jnp.arange(n).reshape(n, 1, 1)
        return data[bidx, :, yi, xi].transpose(0, 3, 1, 2)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
            + v10 * (1 - wx) * wy + v11 * wx * wy)
