"""Python side of the C NDArray/imperative API (src/c_api.cc).

Reference parity: the NDArray + imperative-invoke slice of
include/mxnet/c_api.h (MXNDArrayCreateEx:529, MXNDArraySyncCopyFromCPU,
MXImperativeInvokeEx:887) that cpp-package's training path drives. The
C layer (libmxtpu_predict.so) holds PyObject handles to the NDArrays
returned here; every tensor crossing the boundary is float32 (the C
surface's declared contract, like c_predict_api).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["create_ndarray", "copy_from", "copy_to", "get_shape",
           "imperative_invoke"]


def create_ndarray(shape, dtype="float32"):
    from .ndarray.ndarray import zeros
    return zeros(tuple(int(s) for s in shape), dtype=dtype)


def copy_from(nd, buf):
    """Fill ``nd`` from a C float32 buffer (memoryview/bytes).

    The buffer is OWNED BY THE C CALLER and may be freed the moment
    this returns (the cpp demo passes stack temporaries), while jax on
    CPU can zero-copy-alias numpy arrays — so the bytes must be copied
    into Python-owned memory here, not wrapped."""
    arr = _np.frombuffer(buf, dtype=_np.float32)
    if arr.size != nd.size:
        raise ValueError("SyncCopyFromCPU: buffer has %d floats, NDArray "
                         "has %d elements" % (arr.size, nd.size))
    nd._sync_copyfrom(arr.reshape(nd.shape).copy())
    return None


def copy_to(nd):
    """Return a C-contiguous float32 numpy array of ``nd``'s contents
    (the sync point — blocks until the value is ready)."""
    return _np.ascontiguousarray(nd.asnumpy(), dtype=_np.float32)


def get_shape(nd):
    return [int(s) for s in nd.shape]


def imperative_invoke(op_name, inputs, keys, vals):
    """Invoke a registered operator eagerly (reference
    MXImperativeInvokeEx). ``keys``/``vals`` are string attribute pairs
    coerced per-op exactly like symbol-JSON attrs. Returns a list of
    output NDArrays."""
    from .ops import registry as _reg
    from .ndarray import dispatch as _dispatch

    op = _reg.get_op(op_name)
    kwargs = dict(zip(list(keys), list(vals)))
    out = _dispatch.invoke(op, tuple(inputs), kwargs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# ----------------------------------------------------------------------
# Symbol / Executor surface (reference c_api_symbolic.cc +
# c_api_executor.cc:220 MXExecutorSimpleBind) — handles are PyObjects
# of Symbol / Executor; src/c_api.cc marshals the C side.
# ----------------------------------------------------------------------
def symbol_from_json(json_str):
    from .symbol import load_json
    return load_json(json_str)


def symbol_from_file(fname):
    from .symbol import load
    return load(fname)


def symbol_list_arguments(sym):
    return list(symbol_resolve(sym).list_arguments())


def symbol_list_auxiliary_states(sym):
    return list(symbol_resolve(sym).list_auxiliary_states())


def symbol_list_outputs(sym):
    return list(symbol_resolve(sym).list_outputs())


def symbol_tojson(sym):
    return symbol_resolve(sym).tojson()


def executor_simple_bind(sym, keys, shapes, grad_req="write"):
    """simple_bind on the default (cpu in the embedded runtime) context;
    ``keys``/``shapes`` give the input shapes, everything else infers
    (reference MXExecutorSimpleBind's 30-arg marshal collapses to this)."""
    from .context import cpu
    kwargs = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    return symbol_resolve(sym).simple_bind(ctx=cpu(), grad_req=grad_req,
                                           **kwargs)


def executor_arg_array(ex, name):
    arr = ex.arg_dict.get(name)
    if arr is None:
        raise KeyError("executor has no argument '%s' (args: %s)"
                       % (name, list(ex.arg_dict)))
    return arr


def executor_grad_array(ex, name):
    arr = ex.grad_dict.get(name)
    if arr is None:
        raise KeyError("executor has no gradient for '%s'" % name)
    return arr


def executor_aux_array(ex, name):
    arr = ex.aux_dict.get(name)
    if arr is None:
        raise KeyError("executor has no aux state '%s'" % name)
    return arr


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))
    return None


def executor_backward(ex):
    ex.backward()
    return None


def executor_outputs(ex):
    return list(ex.outputs)


def ndarray_copy_from(dst, src):
    """In-place dst <- src (the C trainer's functional-update writeback;
    reference _copyto). Shapes must match exactly — silently adopting a
    different shape would corrupt a bound executor's live argument."""
    if tuple(src.shape) != tuple(dst.shape):
        raise ValueError("MXNDArrayCopyFrom: shape mismatch %s vs %s"
                         % (tuple(src.shape), tuple(dst.shape)))
    dst._set_data(src._data.astype(dst._data.dtype))
    return None


# ----------------------------------------------------------------------
# KVStore surface (reference MXKVStoreCreate/Init/Push/Pull,
# include/mxnet/c_api.h MXKVStore*) — handles are PyObjects of KVStore.
# ----------------------------------------------------------------------
def kvstore_create(name):
    from . import kvstore
    return kvstore.create(name)


def kvstore_init(kv, keys, values):
    kv.init(list(keys), list(values))
    return None


def kvstore_push(kv, keys, values, priority):
    kv.push(list(keys), list(values), priority=int(priority))
    return None


def kvstore_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))
    return None


def kvstore_set_optimizer_sgd(kv, lr, momentum, wd, rescale_grad):
    """The C trainer's optimizer-on-store hook (reference
    MXKVStoreSetOptimizer pickles arbitrary optimizers; the C surface
    exposes the SGD family directly)."""
    from . import optimizer as _opt
    kv.set_optimizer(_opt.SGD(learning_rate=float(lr),
                              momentum=float(momentum), wd=float(wd),
                              rescale_grad=float(rescale_grad)))
    return None


def kvstore_rank(kv):
    return int(kv.rank)


def kvstore_num_workers(kv):
    return int(kv.num_workers)


def kvstore_barrier(kv):
    kv.barrier()
    return None


# ----------------------------------------------------------------------
# atom-level symbol composition (reference c_api.h:1111
# MXSymbolListAtomicSymbolCreators / MXSymbolCreateAtomicSymbol /
# MXSymbolCompose / MXSymbolCreateVariable)
# ----------------------------------------------------------------------
def list_atomic_symbol_creators():
    """Creator handles are the op names themselves (our registry is
    name-keyed; the reference's AtomicSymbolCreator pointers are an
    artifact of its C++ op registry)."""
    from .ops import registry as _reg
    return sorted(_reg.list_ops())


def create_atomic_symbol(op_name, keys, vals):
    """An un-composed op node: record op + attrs, inputs arrive at
    MXSymbolCompose time (reference two-phase Create/Compose protocol)."""
    from .ops import registry as _reg
    _reg.get_op(op_name)  # raise early on unknown op
    return {"op": op_name, "attrs": dict(zip(list(keys), list(vals))),
            "composed": None}


def create_variable(name):
    from . import symbol as _sym
    return _sym.Variable(name)


def symbol_compose(atom, name, keys, args):
    """Bind inputs to an atomic node IN PLACE (the reference mutates the
    handle); positional when ``keys`` is empty, else keyword."""
    from . import symbol as _sym
    op = getattr(_sym, atom["op"])
    attrs = dict(atom["attrs"])
    if name:
        attrs["name"] = name
    inputs = [a["composed"] if isinstance(a, dict) else a for a in args]
    if any(i is None for i in inputs):
        raise ValueError("MXSymbolCompose: an input atom was never composed")
    if keys:
        attrs.update(zip(list(keys), inputs))
        atom["composed"] = op(**attrs)
    else:
        atom["composed"] = op(*inputs, **attrs)
    return None


def symbol_resolve(handle):
    """The Symbol behind a handle (atoms must be composed first)."""
    if isinstance(handle, dict):
        if handle["composed"] is None:
            raise ValueError("atomic symbol used before MXSymbolCompose")
        return handle["composed"]
    return handle


# ----------------------------------------------------------------------
# autograd (reference c_api.h:963 MXAutogradMarkVariables /
# MXAutogradBackwardEx / MXAutogradSetIsRecording|Training)
# ----------------------------------------------------------------------
def autograd_set_recording(flag):
    from . import autograd as _ag
    prev = _ag.is_recording()
    _ag._state.recording = bool(flag)
    return int(prev)


def autograd_set_training(flag):
    from . import autograd as _ag
    prev = _ag.is_training()
    _ag._state.training = bool(flag)
    return int(prev)


def autograd_mark_variables(variables, grad_reqs, gradients):
    from . import autograd as _ag
    reqs = [{0: "null", 1: "write", 2: "add"}.get(int(r), "write")
            for r in grad_reqs]
    _ag.mark_variables(list(variables), list(gradients), reqs)
    return None


def autograd_backward(outputs, out_grads, retain_graph, train_mode):
    from . import autograd as _ag
    heads = list(outputs)
    ograds = None if not out_grads else list(out_grads)
    _ag.backward(heads, ograds, retain_graph=bool(retain_graph),
                 train_mode=bool(train_mode))
    return None


def ndarray_get_grad(nd):
    grad = getattr(nd, "grad", None)
    if grad is None:
        raise ValueError("NDArray has no gradient buffer; call "
                         "MXAutogradMarkVariables first")
    return grad


# ----------------------------------------------------------------------
# data iterators (reference MXListDataIters / MXDataIterCreateIter /
# Next / GetData / GetLabel / GetPadNum)
# ----------------------------------------------------------------------
_DATA_ITERS = ["MNISTIter", "ImageRecordIter", "NDArrayIter", "CSVIter"]


def list_data_iters():
    return list(_DATA_ITERS)


def create_data_iter(iter_name, keys, vals):
    """Instantiate a DataIter from string kwargs (the reference's
    creator-handle + param-string protocol)."""
    from . import io as _io
    import ast
    if iter_name not in _DATA_ITERS:
        raise ValueError(f"unknown data iter {iter_name}")
    kwargs = {}
    for k, v in zip(list(keys), list(vals)):
        try:
            kwargs[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    if iter_name == "NDArrayIter":
        # C callers hand data/label via synthetic_* sizes or file paths;
        # support shape-spec strings "(N, C)" filled from a seeded rng so
        # a pure-C program can drive training without numpy
        data_shape = kwargs.pop("data_gen_shape", None)
        label_classes = kwargs.pop("label_gen_classes", None)
        if data_shape is not None:
            rng = _np.random.RandomState(int(kwargs.pop("seed", 0)))
            data = rng.uniform(-1, 1, data_shape).astype(_np.float32)
            n = data_shape[0]
            # learnable rule so C demos can assert convergence: the label
            # quantile-bins the first feature-half's sum minus the
            # second's into `label_gen_classes` classes (2 -> the simple
            # "first half outsums second" rule)
            n_cls = int(label_classes or 2)
            flat = data.reshape(n, -1)
            half = flat.shape[1] // 2
            margin = flat[:, :half].sum(1) - flat[:, half:].sum(1)
            edges = _np.quantile(margin, _np.linspace(0, 1, n_cls + 1)[1:-1])
            label = _np.digitize(margin, edges).astype(_np.float32)
            kwargs["data"] = data
            kwargs["label"] = label
        return _io.NDArrayIter(**kwargs)
    return getattr(_io, iter_name)(**kwargs)


def data_iter_next(it):
    try:
        batch = next(it)
    except StopIteration:
        return None
    return batch


def data_iter_reset(it):
    it.reset()
    return None


def data_iter_get_data(batch):
    return batch.data[0]


def data_iter_get_label(batch):
    return batch.label[0]


def data_iter_get_pad(batch):
    return int(batch.pad or 0)
