"""Python side of the C NDArray/imperative API (src/c_api.cc).

Reference parity: the NDArray + imperative-invoke slice of
include/mxnet/c_api.h (MXNDArrayCreateEx:529, MXNDArraySyncCopyFromCPU,
MXImperativeInvokeEx:887) that cpp-package's training path drives. The
C layer (libmxtpu_predict.so) holds PyObject handles to the NDArrays
returned here; every tensor crossing the boundary is float32 (the C
surface's declared contract, like c_predict_api).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["create_ndarray", "copy_from", "copy_to", "get_shape",
           "imperative_invoke"]


def create_ndarray(shape, dtype="float32"):
    from .ndarray.ndarray import zeros
    return zeros(tuple(int(s) for s in shape), dtype=dtype)


def copy_from(nd, buf):
    """Fill ``nd`` from a C float32 buffer (memoryview/bytes).

    The buffer is OWNED BY THE C CALLER and may be freed the moment
    this returns (the cpp demo passes stack temporaries), while jax on
    CPU can zero-copy-alias numpy arrays — so the bytes must be copied
    into Python-owned memory here, not wrapped."""
    arr = _np.frombuffer(buf, dtype=_np.float32)
    if arr.size != nd.size:
        raise ValueError("SyncCopyFromCPU: buffer has %d floats, NDArray "
                         "has %d elements" % (arr.size, nd.size))
    nd._sync_copyfrom(arr.reshape(nd.shape).copy())
    return None


def copy_to(nd):
    """Return a C-contiguous float32 numpy array of ``nd``'s contents
    (the sync point — blocks until the value is ready)."""
    return _np.ascontiguousarray(nd.asnumpy(), dtype=_np.float32)


def get_shape(nd):
    return [int(s) for s in nd.shape]


def imperative_invoke(op_name, inputs, keys, vals):
    """Invoke a registered operator eagerly (reference
    MXImperativeInvokeEx). ``keys``/``vals`` are string attribute pairs
    coerced per-op exactly like symbol-JSON attrs. Returns a list of
    output NDArrays."""
    from .ops import registry as _reg
    from .ndarray import dispatch as _dispatch

    op = _reg.get_op(op_name)
    kwargs = dict(zip(list(keys), list(vals)))
    out = _dispatch.invoke(op, tuple(inputs), kwargs)
    return list(out) if isinstance(out, (list, tuple)) else [out]
