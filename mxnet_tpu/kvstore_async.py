"""Asynchronous parameter-server kvstore (``dist_async``).

Reference parity: src/kvstore/kvstore_dist_server.h:262-300 — in async
mode the server applies every worker push to the stored value
IMMEDIATELY (Hogwild-style, no per-key barrier counting pushes from all
workers), and workers run free: a fast worker's pushes and pulls never
wait for a slow one. This is a genuinely different capability from the
collective ``dist_sync`` (kvstore_dist.py): collectives are barriers by
construction, so async semantics need real server state. The TPU-native
shape of that state is a host-side service — gradients are small relative
to activations, DCN-bound either way, and the server never touches an
accelerator — so the server here is a threaded TCP service over
length-prefixed pickles with one lock per key:

* ``push``  — decompress if needed, then apply under the key's lock:
  ``updater(key, grad, stored)`` when an optimizer/updater is installed
  (the reference's optimizer-on-server, ``set_optimizer``), else
  ``stored += grad`` (the reference's AssignOrPlus aggregation).
* ``pull``  — return the CURRENT value; no wait for other workers
  (polls briefly only until the key is first initialized).
* ``init``  — first writer wins (idempotent across workers; reference
  kvstore_dist.h:181-197 has worker 0 push init).
* ``barrier`` — explicit Postoffice-style barrier for the rare code that
  wants one (init fences, shutdown); never used by push/pull.

Topology (reference DMLC names): ``tools/launch.py -n W -s S`` spawns S
server processes (DMLC_ROLE=server, kvstore_server.py) on
DMLC_PS_ROOT_PORT..+S-1 and W free-running workers; keys shard across
servers by stable hash (the reference's EncodeDefaultKey ring). With no
launcher (single process, DMLC_NUM_SERVER unset) the store spawns one
in-process daemon server — ``mx.kv.create('dist_async')`` then works
standalone with the same immediate-apply semantics.
"""
from __future__ import annotations

import hmac
import os
import pickle
import secrets as _secrets
import socket
import socketserver
import struct
import threading
import time

import numpy as _np

from .base import MXNetError
from .kvstore import KVStore, _key_value, _updater_key

__all__ = ["KVStoreDistAsync", "ParamServer", "serve_forever"]

_HDR = struct.Struct(">Q")
_MAC_BYTES = 32  # HMAC-SHA256


def _job_secret():
    """Per-job wire secret. launch.py generates one and exports
    MXTPU_PS_SECRET to every worker/server; standalone mode generates a
    process-local one. The wire is pickle, so every frame carries an
    HMAC-SHA256 over the payload — a peer without the secret cannot get
    a frame deserialized (ADVICE r4: pickle over TCP is an arbitrary-
    code-execution surface without authentication)."""
    return os.environ.get("MXTPU_PS_SECRET", "").encode()


def _mac(secret, payload):
    return hmac.new(secret, payload, "sha256").digest()


def _send_msg(sock, obj, secret=b""):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + _mac(secret, payload) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock, secret=b""):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    mac = _recv_exact(sock, _MAC_BYTES)
    payload = _recv_exact(sock, n)
    if not hmac.compare_digest(mac, _mac(secret, payload)):
        # authentication failure: never unpickle the payload
        raise ConnectionError("bad frame MAC (wrong or missing "
                              "MXTPU_PS_SECRET)")
    return pickle.loads(payload)


class _App:
    """Per-app (per KVStore instance) server state — the analog of a
    ps-lite customer id: each ``mx.kv.create('dist_async')`` gets its own
    key space, updater, compression config, and barrier."""

    def __init__(self):
        self.store = {}             # key -> np.ndarray (current value)
        self.locks = {}             # key -> threading.Lock
        self.updater = None
        self.compression = None
        self.barrier_gen = 0
        self.barrier_count = 0
        self.barrier_cv = threading.Condition()
        self.push_counts = {}       # key -> applied pushes (observability)
        # at-most-once RPC: (worker_rank) -> (last_seq, last_response).
        # A client only ever retransmits its LAST request (synchronous
        # protocol), so caching one response per worker makes every
        # non-idempotent op (push under an updater) safe across
        # connection resets.
        self.last_rpc = {}
        # barrier needs entry-time dedupe too: its response is only
        # cached AFTER release, so a retransmit of a still-blocked
        # barrier must not count twice. worker -> (seq, gen at entry).
        self.barrier_entered = {}


class ParamServer:
    """Server state + request handling (one instance per server process
    or per in-process daemon thread)."""

    def __init__(self, num_workers):
        self._num_workers = int(num_workers)
        self._apps = {}
        self._meta_lock = threading.Lock()
        # bind every dependency a request handler needs NOW (constructed
        # on a thread where importing is safe); handler threads must
        # never import — they can run while another thread is inside
        # ``import mxnet_tpu`` and would deadlock on the import lock
        from . import optimizer as _opt
        from .ndarray import NDArray as _NDArray
        from .parallel.compression import TwoBitCompressor as _TwoBit
        import jax.numpy as _jnp
        self._mod_opt = _opt
        self._NDArray = _NDArray
        self._TwoBit = _TwoBit
        self._jnp = _jnp

    # ------------------------------------------------------------------
    def _app(self, app_id):
        with self._meta_lock:
            app = self._apps.get(app_id)
            if app is None:
                app = self._apps[app_id] = _App()
            return app

    def _lock_for(self, app, key):
        with self._meta_lock:
            lk = app.locks.get(key)
            if lk is None:
                lk = app.locks[key] = threading.Lock()
            return lk

    def _decompress(self, app, wire):
        kind, packed, shape, dtype = wire
        if kind != "2bit":
            raise MXNetError("unknown wire compression %r" % kind)
        if app.compression is None:
            raise MXNetError("server has no compression configured")
        arr = app.compression.decompress(
            _np.frombuffer(packed, _np.uint8), tuple(shape), dtype)
        return _np.asarray(arr, dtype)

    def _apply(self, app, key, grad):
        """The async core: apply THIS push now, under only this key's
        lock (kvstore_dist_server.h async mode — no merge buffer, no
        push counting)."""
        lk = self._lock_for(app, key)
        with lk:
            stored = app.store.get(key)
            if stored is None:
                raise MXNetError("push to uninitialized key %r" % key)
            if app.updater is not None:
                NDArray, jnp = self._NDArray, self._jnp
                w = NDArray(jnp.asarray(stored))
                app.updater(_updater_key(key), NDArray(jnp.asarray(grad)),
                            w)
                app.store[key] = _np.asarray(w.asnumpy(), stored.dtype)
            else:
                app.store[key] = stored + grad.astype(stored.dtype)
            app.push_counts[key] = app.push_counts.get(key, 0) + 1

    # ------------------------------------------------------------------
    def handle(self, msg):
        op = msg["op"]
        app = self._app(msg.get("app", 0))
        wkr, seq = msg.get("wkr"), msg.get("seq")
        if wkr is not None and seq is not None:
            with self._meta_lock:
                last = app.last_rpc.get(wkr)
            if last is not None and last[0] == seq:
                return last[1]          # retransmit of the last request
            resp = self._handle_op(op, app, msg)
            if not resp.get("stop"):
                with self._meta_lock:
                    app.last_rpc[wkr] = (seq, resp)
            return resp
        return self._handle_op(op, app, msg)

    def _handle_op(self, op, app, msg):
        if op == "init":
            key, val = msg["key"], msg["value"]
            lk = self._lock_for(app, key)
            with lk:
                if key not in app.store:       # first writer wins
                    app.store[key] = _np.asarray(val)
            return {"ok": True}
        if op == "push":
            grad = msg["value"]
            if isinstance(grad, tuple):
                grad = self._decompress(app, grad)
            self._apply(app, msg["key"], grad)
            return {"ok": True}
        if op == "pull_rows":
            key = msg["key"]
            rows = _np.asarray(msg["rows"], _np.int64)
            lk = self._lock_for(app, key)
            with lk:
                val = app.store.get(key)
                if val is None:
                    return {"ok": False,
                            "error": "key %r not initialized" % (key,)}
                return {"ok": True, "value": val[rows], "rows": rows}
        if op == "pull":
            key = msg["key"]
            deadline = time.time() + msg.get("timeout", 60.0)
            while True:
                lk = self._lock_for(app, key)
                with lk:
                    val = app.store.get(key)
                    if val is not None:
                        return {"ok": True, "value": val,
                                "pushes": app.push_counts.get(key, 0)}
                if time.time() > deadline:
                    return {"ok": False,
                            "error": "key %r not initialized" % (key,)}
                time.sleep(0.01)
        if op == "set_optimizer":
            optimizer = pickle.loads(msg["optimizer"])
            app.updater = self._mod_opt.get_updater(optimizer)
            return {"ok": True}
        if op == "set_gradient_compression":
            app.compression = self._TwoBit(
                threshold=float(msg["params"].get("threshold", 0.5)))
            return {"ok": True}
        if op == "barrier":
            n = msg.get("count", self._num_workers)
            wkr, seq = msg.get("wkr"), msg.get("seq")
            with app.barrier_cv:
                entered = app.barrier_entered.get(wkr)
                if entered is not None and entered[0] == seq:
                    gen = entered[1]       # retransmit: already counted
                else:
                    gen = app.barrier_gen
                    app.barrier_entered[wkr] = (seq, gen)
                    app.barrier_count += 1
                if app.barrier_count >= n:
                    app.barrier_gen += 1
                    app.barrier_count = 0
                    app.barrier_cv.notify_all()
                elif app.barrier_gen == gen:
                    while app.barrier_gen == gen:
                        if not app.barrier_cv.wait(timeout=120):
                            # roll this worker back OUT of the barrier so a
                            # later retry re-enters cleanly instead of
                            # double-counting (ADVICE r4); without this the
                            # barrier could release with a worker absent.
                            if app.barrier_gen == gen:
                                app.barrier_count -= 1
                                app.barrier_entered.pop(wkr, None)
                            return {"ok": False, "error": "barrier timeout"}
            return {"ok": True}
        if op == "ping":
            return {"ok": True, "apps": len(self._apps)}
        if op == "stop":
            return {"ok": True, "stop": True}
        return {"ok": False, "error": "unknown op %r" % op}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        secret = self.server.secret
        while True:
            try:
                msg = _recv_msg(self.request, secret)
            except (ConnectionError, OSError):
                return
            resp = self.server.param_server.handle(msg)
            try:
                _send_msg(self.request, resp, secret)
            except (ConnectionError, OSError):
                return
            if resp.get("stop"):
                threading.Thread(target=self.server.shutdown,
                                 daemon=True).start()
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve_forever(host, port, num_workers, secret=None):
    """Run one parameter server (blocking). kvstore_server.py calls this
    for DMLC_ROLE=server processes."""
    srv = _TCPServer((host, port), _Handler)
    srv.param_server = ParamServer(num_workers)
    srv.secret = _job_secret() if secret is None else secret
    srv.serve_forever()


def _spawn_inprocess_server(port, num_workers, secret):
    srv = _TCPServer(("127.0.0.1", port), _Handler)
    srv.param_server = ParamServer(num_workers)
    srv.secret = secret
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mxtpu-param-server")
    t.start()
    return srv


class KVStoreDistAsync(KVStore):
    """Worker-side client of the async parameter servers. Free-running:
    no method here ever waits on another worker (except ``barrier``).

    Each instance gets an app id (a ps-lite-customer-id analog) from a
    per-process counter, namespacing its keys/updater/barrier on the
    servers — workers must therefore create their dist_async stores in
    the same order (the reference's customer ids have the same
    contract)."""

    _next_app = [0]
    _captures_local_state = False   # state lives on the servers

    def __init__(self, name="dist_async"):
        super().__init__(name)
        # push is overridden: the compiled bucketed engine never
        # engages and every push is an eager wire round-trip — signal
        # it once + count it (kvstore_fallbacks), like kvstore_dist
        from .kvstore import _note_fallback
        _note_fallback(
            "legacy_dist_kvstore:%s" % name,
            detail="async parameter-server store, every push is eager "
                   "per-key (Hogwild semantics need it)")
        self._app_id = KVStoreDistAsync._next_app[0]
        KVStoreDistAsync._next_app[0] += 1
        self._rank = int(os.environ.get("MXTPU_WORKER_RANK", "0"))
        self._nworkers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        nserv = int(os.environ.get("DMLC_NUM_SERVER", "0"))
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "0")) or 9091
        self._own_server = None
        self._secret = _job_secret()
        if nserv <= 0:
            if self._nworkers > 1:
                raise MXNetError(
                    "dist_async with %d workers needs parameter-server "
                    "processes: launch with tools/launch.py -n %d -s <S> "
                    "(an in-process fallback server would give every "
                    "worker its own isolated store)"
                    % (self._nworkers, self._nworkers))
            # standalone/dev mode (single worker): in-process daemon server
            import socket as _socket
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            host = "127.0.0.1"
            if not self._secret:
                # standalone: nobody shares this server, so mint a
                # process-local secret rather than running unauthenticated
                self._secret = _secrets.token_bytes(16)
            self._own_server = _spawn_inprocess_server(port, self._nworkers,
                                                       self._secret)
            nserv = 1
        self._servers = [(host, port + i) for i in range(nserv)]
        self._socks = [None] * nserv
        self._sock_locks = [threading.Lock() for _ in range(nserv)]
        # Per-shard RPC sequence for at-most-once retransmit dedupe.
        # Server-side dedupe state is per server, so independent per-shard
        # counters (each guarded by that shard's socket lock) cannot race
        # across threads the way one shared counter could (ADVICE r4).
        self._rpc_seq = [0] * nserv

    # ------------------------------------------------------------------
    def _server_of(self, key):
        # stable shard ring (reference EncodeDefaultKey): same key ->
        # same server on every worker
        import zlib
        return zlib.crc32(str(key).encode()) % len(self._servers)

    def _request(self, sidx, msg, retries=240):
        # generous connect retries: the server process imports the full
        # package before listening (~seconds on a loaded host)
        # fresh copy per (request, shard): callers (and _all_servers)
        # reuse msg dicts, and a seq stamped for one shard must never
        # leak to another — each server dedupes on its own counter line
        msg = dict(msg)
        msg.setdefault("app", self._app_id)
        msg.setdefault("wkr", self._rank)
        with self._sock_locks[sidx]:
            self._rpc_seq[sidx] += 1
            msg["seq"] = self._rpc_seq[sidx]
            for attempt in range(retries):
                sock = self._socks[sidx]
                if sock is None:
                    try:
                        sock = socket.create_connection(
                            self._servers[sidx], timeout=120)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        self._socks[sidx] = sock
                    except OSError:
                        time.sleep(0.25)
                        continue
                try:
                    _send_msg(sock, msg, self._secret)
                    resp = _recv_msg(sock, self._secret)
                except (ConnectionError, OSError):
                    self._socks[sidx] = None
                    time.sleep(0.25)
                    continue
                if not resp.get("ok"):
                    raise MXNetError("param server: %s"
                                     % resp.get("error", "unknown"))
                return resp
        raise MXNetError("cannot reach param server %s:%d"
                         % self._servers[sidx])

    def _all_servers(self, msg):
        return [self._request(i, msg) for i in range(len(self._servers))]

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nworkers

    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            self._request(self._server_of(k),
                          {"op": "init", "key": k,
                           "value": _np.asarray(vlist[0].asnumpy())})

    def push(self, key, value, priority=0):
        """Local reduce, then ship to the key's server, which applies it
        IMMEDIATELY — returns as soon as this worker's push is applied;
        never waits for other workers."""
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            reduced = self._local_reduce(vlist)
            if self._compression is not None:
                packed, shape, dtype = self._compress_wire(k, reduced)
                wire = ("2bit", _np.asarray(packed, _np.uint8).tobytes(),
                        tuple(shape), _np.dtype(dtype).str)
                self._request(self._server_of(k),
                              {"op": "push", "key": k, "value": wire})
            else:
                self._request(self._server_of(k),
                              {"op": "push", "key": k,
                               "value": _np.asarray(reduced.asnumpy())})

    def _compress_wire(self, k, grad):
        residual = self._get_residual((k, "wire"), grad)
        packed, new_residual = self._compression.compress(
            grad._data, residual._data)
        residual._set_data(new_residual)
        return _np.asarray(packed), grad.shape, grad._data.dtype

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Fetch the CURRENT server value — whatever pushes have landed
        so far (async staleness is the semantics, not a bug)."""
        import jax.numpy as jnp
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            resp = self._request(self._server_of(k),
                                 {"op": "pull", "key": k})
            val = jnp.asarray(resp["value"])
            for o in olist:
                o._set_data(val.astype(o.dtype))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Reference PullRowSparse over the async wire: the server slices
        the requested rows (op ``pull_rows``) so only those rows cross
        the wire — no dense transfer, no shared-state mutation."""
        if row_ids is None:
            self.pull(key, out=out, priority=priority)
            return
        import jax.numpy as jnp
        from .kvstore import _key_value
        from .ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray

        keys, outs = _key_value(key, out)
        n_out = sum(len(olist) for olist in outs)
        if isinstance(row_ids, NDArray):
            rid_list = [row_ids] * n_out
        else:
            rid_list = list(row_ids)
            if len(rid_list) != n_out:
                raise MXNetError(
                    "row_sparse_pull: %d row_ids for %d out arrays"
                    % (len(rid_list), n_out))
        i = 0
        for k, olist in zip(keys, outs):
            for o in olist:
                rows = _np.unique(
                    _np.asarray(rid_list[i].asnumpy(), _np.int64))
                i += 1
                resp = self._request(self._server_of(k),
                                     {"op": "pull_rows", "key": k,
                                      "rows": rows})
                vals = jnp.asarray(resp["value"])
                if isinstance(o, RowSparseNDArray):
                    # _set_data re-derives the (data, indices) pair from
                    # the dense view; zero rows drop out
                    full = jnp.zeros(o.shape, vals.dtype) \
                        .at[jnp.asarray(rows)].set(vals)
                    o._set_data(full.astype(o.dtype))
                else:
                    dense = jnp.asarray(o._data) \
                        .at[jnp.asarray(rows)].set(vals)
                    o._set_data(dense.astype(o.dtype))

    def pull_with_meta(self, key):
        """(value, applied_push_count) — observability used by tests to
        demonstrate unsynchronized interleaving."""
        resp = self._request(self._server_of(key),
                             {"op": "pull", "key": key})
        return resp["value"], resp["pushes"]

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to every server (reference
        kvstore.py set_optimizer → server-side Updater)."""
        payload = pickle.dumps(optimizer)
        self._all_servers({"op": "set_optimizer", "optimizer": payload})

    def set_updater(self, updater):
        # host-side updater objects can't cross the wire in general; the
        # reference has the same restriction (only optimizers pickle).
        raise MXNetError(
            "dist_async runs the update on the server: use set_optimizer() "
            "(reference kvstore_dist_server.h ApplyUpdates)")

    def set_gradient_compression(self, compression_params):
        super().set_gradient_compression(compression_params)
        params = dict(compression_params)
        self._all_servers({"op": "set_gradient_compression",
                           "params": params})

    def barrier(self):
        """Explicit Postoffice-style barrier (never implicit in any
        push/pull)."""
        self._request(0, {"op": "barrier", "count": self._nworkers})

    def get_num_dead_node(self, node_id=0, timeout=60):
        dead = 0
        for i in range(len(self._servers)):
            try:
                self._request(i, {"op": "ping"}, retries=2)
            except MXNetError:
                dead += 1
        return dead

    @property
    def is_recovery(self):
        return os.environ.get("DMLC_IS_RECOVERY", "0") == "1"
