"""Optimizers (reference parity: python/mxnet/optimizer.py, 17 optimizers).

Each optimizer drives a *fused update op* (ops/optimizer_ops.py) so the whole
update is one XLA computation per parameter — mirroring the reference where
optimizers call sgd_update/adam_update kernels (src/operator/optimizer_op.cc).
Multi-precision (fp32 master weights for fp16/bf16 params) follows
reference optimizer.py:445-545.
"""
from __future__ import annotations

import math
import pickle

import numpy as _np

from .base import MXNetError
from .ndarray import (NDArray, zeros, ones, array, sgd_update, sgd_mom_update,
                      mp_sgd_update, mp_sgd_mom_update, adam_update,
                      signsgd_update, signum_update, rmsprop_update,
                      rmspropalex_update, ftrl_update, adagrad_update)
from . import ndarray as nd

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "NAG", "SGLD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam",
           "FTML", "DCASGD", "LBSGD", "LAMB", "Test", "Updater",
           "get_updater", "create", "register", "FUSED_EAGER_WAIVERS"]

_OPT_REGISTRY = {}

# Optimizers that intentionally stay on the eager per-key path. The
# analyze ``optfused`` pass (tools/check_static.py, tier-1) requires
# every ``@register``-ed optimizer to either describe its update via
# ``_fused_sig`` or sit here with a reason — new optimizers can't
# silently ship eager-only.
FUSED_EAGER_WAIVERS = {
    "Signum": "sign-of-momentum update couples wd_lh into the weight "
              "step; niche optimizer, fuse on demand",
    "SignSGD": "inherits Signum's eager path",
    "NAG": "nesterov look-ahead mutates the momentum mid-formula; "
           "fuse together with Signum if demand appears",
    "SGLD": "draws fresh host-side Langevin noise every update — not a "
            "pure function of (weight, grad, state)",
    "GroupAdaGrad": "embedding-table optimizer; rides the compiled "
                    "row_sparse pipeline via _fused_sparse_sig instead",
    "AdaDelta": "accumulator pair updated through aliased in-place "
                "views; rarely used at scale",
    "Ftrl": "piecewise-zero proximal update (sparse-regime optimizer)",
    "FTML": "t-dependent denominator already runs as one fused XLA op "
            "per key via nd.ftml_update",
    "DCASGD": "delay compensation snapshots the full previous weight — "
              "async-SGD only, never on the sync hot path",
    "Test": "conformance-test fixture",
}


def register(klass):
    _OPT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    key = str(name).lower()
    if key not in _OPT_REGISTRY:
        raise MXNetError("unknown optimizer '%s'" % name)
    return _OPT_REGISTRY[key](**kwargs)


def _lazy_sparse(opt, grad):
    """True when the grad is row_sparse and the optimizer opts into the
    reference's lazy (touched-rows-only) update."""
    return (getattr(grad, "stype", "default") == "row_sparse"
            and getattr(opt, "lazy_update", False))


class Optimizer:
    """Base optimizer (reference optimizer.py:33). Tracks per-parameter
    lr/wd multipliers, update counts, and optional fp32 master copies."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) \
            if sym is not None else ()
        self.lr_mult = {}
        self.set_lr_mult({})
        self.wd_mult = {}
        self.set_wd_mult({})

    # -- serialization for kvstore servers (reference set_optimizer) ----
    def dumps(self):
        return pickle.dumps(self)

    @staticmethod
    def loads(data):
        return pickle.loads(data)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (_np.float16, _np.dtype("bfloat16")):
            # master-copy creation stays on device (astype enqueues a
            # cast; no asnumpy round-trip through the host)
            weight_master_copy = weight.astype("float32")
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    # -- the fused-update protocol (docs/TRAINING.md) -------------------
    # An optimizer *describes* its update as a pure jittable program:
    # `_fused_sig()` names a kind registered in fused_update.py plus the
    # trace-static hyperparameters; `_fused_update` is the resulting
    # (params, grads, states, runtime_scalars) -> (params, states) pure
    # function. Everything that changes per step — lr schedules, wd,
    # rescale_grad (ragged batches!), loss scale, per-key bias
    # correction (`_fused_extra`) — is a RUNTIME argument, so steady
    # state never retraces. Multi-precision (inner, weight32) state
    # tuples are first-class: the shared builder peels the master
    # weight off the state and refreshes the low-precision model weight
    # inside the same donated program.

    def _fused_sig(self):
        """Hashable ``(kind, *hypers)`` tuple fully determining the
        pure per-key update (a fused_update.py kind), or None to stay
        on the eager per-key path (then the class must be listed in
        FUSED_EAGER_WAIVERS). The tuple keys every engine's program
        cache, so mutating a hyperparameter in it retraces once."""
        return None

    def _fused_update(self, params, grads, states, runtime_scalars):
        """The described update as a pure jittable program over aligned
        per-key sequences: returns ``(new_params, new_states)``.
        ``runtime_scalars`` carries the per-key ``lr``/``wd`` vectors,
        the ``rescale`` scalar, the ``extra`` matrix from
        `_fused_runtime`, the static per-key ``mp`` flags and the
        static ``use_wd`` short-circuit."""
        from . import fused_update
        sig = self._fused_sig()
        if sig is None:
            raise MXNetError("%s does not describe a fused update"
                             % type(self).__name__)
        return fused_update.bulk_apply(sig)(params, grads, states,
                                            runtime_scalars)

    def _fused_lr(self, index):
        """Per-key runtime lr as consumed by the fused program. Kinds
        that fold time-dependent bias correction into the step size on
        the host (Adam, Adamax) override this; `_update_count` must
        already have run for the key."""
        return self._get_lr(index)

    def _fused_extra(self, ukeys):
        """(n_keys, n_extra) float32 matrix of per-key runtime scalars
        beyond lr/wd (e.g. Nadam's schedule products, LAMB's bias
        corrections). Host-side schedule state is advanced HERE, in
        ukeys order, mirroring the eager per-key sequence."""
        return _np.zeros((len(ukeys), 0), dtype=_np.float32)

    def _fused_runtime(self, ukeys):
        """Advance update counts for ``ukeys`` and collect the runtime
        vectors for one fused step: ``(lr_vec, wd_vec, extra)``."""
        for uk in ukeys:
            self._update_count(uk)
        lr_vec = _np.asarray([self._fused_lr(uk) for uk in ukeys],
                             dtype=_np.float32)
        wd_vec = _np.asarray([self._get_wd(uk) for uk in ukeys],
                             dtype=_np.float32)
        return lr_vec, wd_vec, self._fused_extra(ukeys)

    def _fused_bucket_sig(self):
        """Signature enabling the kvstore compiled bucketed hot path
        (kvstore_fused.py): a hashable tuple fully determining the pure
        per-bucket update, or None to keep updates per-key eager. The
        tuple is part of the bucket-program cache key, so mutating any
        hyperparameter in it retraces exactly once. Defaults to the
        shared fused-update signature."""
        return self._fused_sig()

    def _fused_fit_sig(self):
        """Signature enabling the single-launch fit step
        (module/fused_fit.py, docs/TRAINING.md): the whole
        fwd+bwd+compress+reduce+update traces into ONE donated program
        keyed partly by this tuple. Defaults to the bucket signature —
        an optimizer whose bucket update is pure and shape-generic fuses
        into the fit step the same way; override to opt in/out of
        whole-step fusion separately (rescale_grad stays a runtime
        argument in both, so ragged batches never retrace)."""
        return self._fused_bucket_sig()

    def _fused_sparse_sig(self):
        """Signature enabling the kvstore compiled row_sparse path
        (embedding/engine.py, docs/EMBEDDING.md): a hashable
        ``(kind, hyper, clip)`` tuple fully determining the pure lazy
        per-row apply, or None to keep sparse pushes on the eager
        per-key path. lr/wd/rescale_grad ride as runtime scalars (like
        the dense bucket programs), so schedule steps never retrace;
        the tuple keys the per-table program cache."""
        return None

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (_np.float16, _np.dtype("bfloat16")):
            inner_state, weight32 = state
            g32 = grad.astype("float32")
            self.update(index, weight32, g32, inner_state)
            weight._set_data(weight32._data.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    # -- schedules ------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is set")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """(reference optimizer.py:296) __lr_mult__ attrs then overrides."""
        self.lr_mult = {}
        if self.sym_info:
            attrs, arg_names = self.sym_info
            for name in arg_names:
                if name in attrs and "__lr_mult__" in attrs[name]:
                    self.lr_mult[name] = float(attrs[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """(reference optimizer.py:330) wd defaults to 0 for params whose
        name doesn't end in _weight/_gamma (bias, beta, moving stats)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attrs, arg_names = self.sym_info
            for name in arg_names:
                if name in attrs and "__wd_mult__" in attrs[name]:
                    self.wd_mult[name] = float(attrs[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        else:
            lr *= self.lr_mult.get(name, 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        else:
            wd *= self.wd_mult.get(name, 1.0)
        return wd

    def _common_kwargs(self, index):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD with momentum + multi-precision (reference optimizer.py:445)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype="float32")

    def _fused_sig(self):
        # rescale_grad is NOT part of the signature: gluon Trainer.step
        # rewrites it every call (scale/batch_size), so it rides along as
        # a runtime scalar — a ragged final batch must not retrace.
        # Multi-precision does NOT opt out: (inner, weight32) state
        # tuples are handled by the shared builder.
        return ("sgd", float(self.momentum),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_sparse_sig(self):
        if self.multi_precision or not self.lazy_update:
            return None     # mp tuples / dense semantics stay eager
        return ("sgd", float(self.momentum),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if _lazy_sparse(self, grad):
            from .ndarray import sparse as _sp
            _sp.sparse_sgd_update(weight, grad, state, lr,
                                  momentum=self.momentum, wd=wd, **kw)
        elif state is not None:
            sgd_mom_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                           momentum=self.momentum, **kw)
        else:
            sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype in (
            _np.float16, _np.dtype("bfloat16"))
        if not use_mp:
            return self.update(index, weight, grad, state)
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        mom, weight32 = state
        if _lazy_sparse(self, grad):
            # lazy rows on the fp32 master, then refresh the model copy
            from .ndarray import sparse as _sp
            _sp.sparse_sgd_update(weight32, grad.astype("float32"), mom, lr,
                                  momentum=self.momentum, wd=wd, **kw)
            weight._set_data(weight32._data.astype(weight.dtype))
        elif mom is not None:
            mp_sgd_mom_update(weight, grad, mom, weight32, out=weight, lr=lr,
                              wd=wd, momentum=self.momentum, **kw)
        else:
            mp_sgd_update(weight, grad, weight32, out=weight, lr=lr, wd=wd, **kw)


@register
class Signum(Optimizer):
    """rahul003's Signum (reference optimizer.py Signum + signum_update op)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if state is not None:
            signum_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                          momentum=self.momentum, wd_lh=self.wd_lh, **kw)
        else:
            signsgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=weight.dtype)
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype="float32"),
                zeros(weight.shape, weight.context, dtype="float32"))

    def _fused_sig(self):
        return ("adam", float(self.beta1), float(self.beta2),
                float(self.epsilon),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_lr(self, index):
        # bias correction folds into the step size on the host exactly
        # like the eager update — lr stays a pure runtime scalar
        t = self._index_update_count[index]
        return self._get_lr(index) * (
            math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        if _lazy_sparse(self, grad):
            from .ndarray import sparse as _sp
            _sp.sparse_adam_update(weight, grad, mean, var, lr,
                                   beta1=self.beta1, beta2=self.beta2,
                                   epsilon=self.epsilon, wd=wd,
                                   **self._common_kwargs(index))
        else:
            adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                        beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon,
                        **self._common_kwargs(index))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype="float32")

    def _fused_sig(self):
        return ("adagrad", float(self.float_stable_eps),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_sparse_sig(self):
        if self.multi_precision:
            return None
        return ("adagrad", float(self.float_stable_eps),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if getattr(grad, "stype", "default") == "row_sparse":
            from .ndarray import sparse as _sp
            _sp.sparse_adagrad_update(weight, grad, state, lr,
                                      epsilon=self.float_stable_eps, wd=wd,
                                      **self._common_kwargs(index))
        else:
            adagrad_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                           epsilon=self.float_stable_eps,
                           **self._common_kwargs(index))


@register
class GroupAdaGrad(Optimizer):
    """Row-wise AdaGrad (reference contrib.GroupAdaGrad,
    group_adagrad_op.cc): ONE adaptive-lr cell per table row —
    ``history += mean(grad^2, axis=1)`` — so the state for a
    (vocab, dim) embedding table is (vocab, 1), a dim-fold smaller than
    AdaGrad's. The recsys default for sharded embedding tables
    (docs/EMBEDDING.md). Like the reference, weight decay is not
    supported (the row-wise denominator makes decoupled wd ill-posed);
    a nonzero ``wd`` raises."""

    def __init__(self, learning_rate=0.01, eps=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        if self.wd != 0.0:
            raise MXNetError("GroupAdaGrad does not support weight decay "
                             "(reference contrib.GroupAdaGrad)")
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros((weight.shape[0], 1), weight.context, dtype="float32")

    def _fused_sparse_sig(self):
        if self.multi_precision:
            return None
        return ("group_adagrad", float(self.float_stable_eps),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        from .ndarray import sparse as _sp
        if getattr(grad, "stype", "default") == "row_sparse":
            _sp.sparse_group_adagrad_update(
                weight, grad, state, lr, epsilon=self.float_stable_eps,
                **self._common_kwargs(index))
        else:
            _sp.group_adagrad_update(
                weight, grad, state, lr, epsilon=self.float_stable_eps,
                **self._common_kwargs(index))


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context, dtype="float32"),
                    zeros(weight.shape, weight.context, dtype="float32"),
                    zeros(weight.shape, weight.context, dtype="float32"))
        return zeros(weight.shape, weight.context, dtype="float32")

    def _fused_sig(self):
        clip = (None if self.clip_gradient is None
                else float(self.clip_gradient))
        # mirrors the eager kwargs: clip_weights rides only when truthy
        cw = float(self.clip_weights) if self.clip_weights else None
        if self.centered:
            return ("rmspropalex", float(self.gamma1), float(self.gamma2),
                    float(self.epsilon), clip, cw)
        return ("rmsprop", float(self.gamma1), float(self.epsilon),
                clip, cw)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            rmspropalex_update(weight, grad, n, g, delta, out=weight, lr=lr,
                               wd=wd, gamma1=self.gamma1, gamma2=self.gamma2,
                               epsilon=self.epsilon, **kw)
        else:
            rmsprop_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                           gamma1=self.gamma1, epsilon=self.epsilon, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        grad += wd * weight
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1 - self.rho) * grad * grad
        current_delta = (nd.sqrt(acc_delta + self.epsilon)
                         / nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta[:] = (self.rho * acc_delta
                        + (1 - self.rho) * current_delta * current_delta)
        weight -= current_delta


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        ftrl_update(weight, grad, z, n, out=weight, lr=lr, wd=wd,
                    lamda1=self.lamda1, beta=self.beta,
                    **self._common_kwargs(index))


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def _fused_sig(self):
        return ("adamax", float(self.beta1), float(self.beta2),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_lr(self, index):
        t = self._index_update_count[index]
        return self._get_lr(index) / (1.0 - self.beta1 ** t)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        m_t, u_t = state
        m_t[:] = self.beta1 * m_t + (1.0 - self.beta1) * grad
        u_t[:] = nd.maximum(self.beta2 * u_t, nd.abs(grad))
        weight -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def _fused_sig(self):
        return ("nadam", float(self.beta1), float(self.beta2),
                float(self.epsilon), float(self.schedule_decay),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_extra(self, ukeys):
        # the shared m_schedule product advances once per key per step;
        # doing it here in ukeys order mirrors the eager sequence, so
        # fused and eager see identical per-key schedule values
        out = _np.zeros((len(ukeys), 5), dtype=_np.float32)
        for i, uk in enumerate(ukeys):
            t = self._index_update_count[uk]
            momentum_t = self.beta1 * (
                1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
            momentum_t_1 = self.beta1 * (
                1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
            self.m_schedule = self.m_schedule * momentum_t
            m_schedule_next = self.m_schedule * momentum_t_1
            out[i] = (momentum_t, momentum_t_1, self.m_schedule,
                      m_schedule_next, 1.0 - self.beta2 ** t)
        return out

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t[:] = self.beta1 * m_t + (1.0 - self.beta1) * grad
        v_t[:] = self.beta2 * v_t + (1.0 - self.beta2) * grad * grad
        grad_prime = grad / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2 ** t)
        m_t_bar = ((1.0 - momentum_t) * grad_prime
                   + momentum_t_1 * m_t_prime)
        weight -= lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d_t, v_t, z_t = state
        # one fused XLA computation (ref optimizer_op.cc FTMLUpdate; note
        # the reference op applies wd to the gradient pre-clip)
        nd.ftml_update(weight, grad, d_t, v_t, z_t, out=weight, lr=lr, t=t,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, wd=wd,
                       rescale_grad=self.rescale_grad,
                       clip_grad=(self.clip_gradient
                                  if self.clip_gradient is not None
                                  else -1.0))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = (zeros(weight.shape, weight.context)
               if self.momentum != 0.0 else None)
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        mom, previous_weight = state
        comp = grad + wd * weight + self.lamda * grad * grad * (
            weight - previous_weight)
        if mom is not None:
            mom[:] = self.momentum * mom - lr * comp
            update = mom
        else:
            update = -lr * comp
        previous_weight._set_data(weight._data)
        weight += update


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise scaling
    (reference optimizer.py LBSGD, simplified warmup handling)."""

    def __init__(self, momentum=0.9, multi_precision=False, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, multi_precision=multi_precision,
                         **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch

    def _fused_sig(self):
        # the per-key LARS norms fold into the fused program as device
        # reductions — no host syncs, unlike the eager _get_lars path
        return ("lbsgd", float(self.momentum),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_sparse_sig(self):
        return None    # LARS over touched rows is ill-defined; stay eager

    def _get_lars(self, weight, g, wd):
        w_norm = float(nd.norm(weight).asscalar())
        g_norm = float(nd.norm(g).asscalar())
        if w_norm > 0 and g_norm > 0:
            return w_norm / (g_norm + wd * w_norm + 1e-9) * 0.001
        return 1.0

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        lr = lr * self._get_lars(weight, grad, wd)
        kw = self._common_kwargs(index)
        if state is not None:
            sgd_mom_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                           momentum=self.momentum, **kw)
        else:
            sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype in (
            _np.float16, _np.dtype("bfloat16"))
        if not use_mp:
            return self.update(index, weight, grad, state)
        # unlike the inherited SGD path, LARS must scale the step taken
        # on the fp32 master (norms computed on master + f32 grad)
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, weight32 = state
        lr = lr * self._get_lars(weight32, grad.astype("float32"), wd)
        kw = self._common_kwargs(index)
        if mom is not None:
            mp_sgd_mom_update(weight, grad, mom, weight32, out=weight,
                              lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            mp_sgd_update(weight, grad, weight32, out=weight, lr=lr,
                          wd=wd, **kw)


@register
class LAMB(Optimizer):
    """Layer-wise Adaptive Moments for Batch training (You et al.,
    arXiv:1904.00962): Adam moments with a per-layer trust ratio
    ``||w|| / ||update||`` scaling the step, the large-batch
    generalization of LARS to adaptive optimizers. The eager path
    computes the two norms on the host (LBSGD idiom); the fused program
    folds them in as device reductions."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype="float32"),
                zeros(weight.shape, weight.context, dtype="float32"))

    def _fused_sig(self):
        return ("lamb", float(self.beta1), float(self.beta2),
                float(self.epsilon), bool(self.bias_correction),
                None if self.clip_gradient is None
                else float(self.clip_gradient))

    def _fused_extra(self, ukeys):
        out = _np.zeros((len(ukeys), 2), dtype=_np.float32)
        for i, uk in enumerate(ukeys):
            t = self._index_update_count[uk]
            out[i] = (1.0 - self.beta1 ** t, 1.0 - self.beta2 ** t)
        return out

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad.astype("float32") * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        m, v = state
        m[:] = self.beta1 * m + (1.0 - self.beta1) * grad
        v[:] = self.beta2 * v + (1.0 - self.beta2) * grad * grad
        if self.bias_correction:
            m_hat = m / (1.0 - self.beta1 ** t)
            v_hat = v / (1.0 - self.beta2 ** t)
        else:
            m_hat, v_hat = m, v
        r = m_hat / (nd.sqrt(v_hat) + self.epsilon) + wd * weight
        w_norm = float(nd.norm(weight).asscalar())
        r_norm = float(nd.norm(r).asscalar())
        ratio = w_norm / r_norm if (w_norm > 0 and r_norm > 0) else 1.0
        weight -= lr * ratio * r


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


class Updater:
    """Applies an optimizer with per-key state (reference optimizer.py:1464);
    picklable so dist kvstore servers can run it."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def set_states(self, states):
        data = pickle.loads(states) if isinstance(states, bytes) else states
        if isinstance(data, tuple) and len(data) == 2:
            self.states, self.optimizer = data
        else:
            self.states = data
        self.states_synced = {k: False for k in self.states}

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
