"""KVStore: key-value parameter synchronization.

Reference parity: include/mxnet/kvstore.h + src/kvstore/ (SURVEY.md §2.3).
TPU-native mapping:

* ``local``/``device`` — single-process aggregation. The reference reduces
  gradient lists on CPU (CommCPU) or via GPU P2P (CommDevice); here the
  per-device gradients are jnp adds that XLA schedules — and when the arrays
  are sharded over a mesh the same add lowers to an ICI all-reduce.
* ``tpu`` (alias ``nccl``) — same API; values that live sharded on a
  ``jax.sharding.Mesh`` reduce over ICI (replaces KVStoreNCCL).
* ``dist_sync`` — multi-process over ``jax.distributed`` collectives
  (kvstore_dist.py), replacing ps-lite ZPush/ZPull; optimizer-on-server
  maps to running the updater on the reduced value (sync by
  construction).
* ``dist_async`` — REAL Hogwild-style parameter servers
  (kvstore_async.py, reference kvstore_dist_server.h async mode):
  launch with ``tools/launch.py -n W -s S``; every push applies
  immediately on the server, workers run free.

2-bit gradient compression (rahul003's signature feature,
src/kvstore/gradient_compression.h) is preserved as an optional transform
applied on push (parallel/compression.py).
"""
from __future__ import annotations

import logging
import os
import pickle

import numpy as _np
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import ndarray as nd
from . import optimizer as opt
from . import telemetry as _telemetry

__all__ = ["KVStore", "create"]

# every push (or whole store) that leaves the compiled hot path counts
# here under a bounded reason label, plus ONE log warning per reason —
# a dist config silently riding the eager per-key loop used to forfeit
# the entire PR2/PR3 launch-count win with no signal at all
FALLBACKS = _telemetry.REGISTRY.counter(
    "kvstore_fallbacks",
    "pushes (or stores) that left the compiled bucketed hot path, "
    "labeled by reason", vital=True)
_warned_fallbacks = set()


def _note_fallback(reason, detail=None, level=logging.WARNING):
    """Count a hot-path fallback and warn ONCE per reason."""
    FALLBACKS.labels(reason=reason).inc()
    if reason not in _warned_fallbacks:
        _warned_fallbacks.add(reason)
        logging.log(
            level,
            "kvstore: falling back to the eager per-key path (%s)%s — "
            "this forfeits the compiled bucketed hot path "
            "(docs/KVSTORE.md); further occurrences are counted in the "
            "kvstore_fallbacks telemetry series without this warning",
            reason, " [%s]" % detail if detail else "")


def create(name="local"):
    """Create a KVStore (reference kvstore.cc:40 string dispatch).
    ``'tpu'``/``'tpu_device'`` (and the legacy ``'nccl'`` alias) build
    the collective multi-host store (kvstore_tpu/, docs/KVSTORE.md)."""
    if not isinstance(name, str):
        raise TypeError("name must be str")
    if name in ("nccl", "tpu", "tpu_device"):
        from .kvstore_tpu import KVStoreTPU
        return KVStoreTPU(name)
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device"):
        return KVStore(name)
    if "async" in name and name.startswith("dist"):
        # real Hogwild-style parameter servers (kvstore_async.py):
        # immediate per-push applies, free-running workers
        from .kvstore_async import KVStoreDistAsync
        return KVStoreDistAsync(name)
    if name.startswith("dist"):
        from .kvstore_dist import KVStoreDist
        return KVStoreDist(name)
    raise MXNetError("unknown kvstore type '%s'" % name)


class KVStore:
    """Single-process kvstore (reference kvstore_local.h:53)."""

    # True when this store's weights/residuals are process-local (or
    # replicated-deterministic) state that mx.checkpoint may capture and
    # Module may key-translate; the legacy dist stores keep server-side
    # state and override this to False (snapshot._plain_kvstore,
    # Module._states_use_kvstore_file read it)
    _captures_local_state = True

    def __init__(self, name="local"):
        self._type = name
        self._store = {}
        self._updater = None
        self._compression = None
        self._compression_residuals = {}
        # compiled bucketed hot path (kvstore_fused.py, docs/KVSTORE.md):
        # on by default for the single-process stores; subclasses that
        # override push never enqueue, so the engine stays inert there
        self._bucketed = os.environ.get("MXNET_KVSTORE_FUSED", "1") != "0"
        self._async_push = os.environ.get(
            "MXNET_KVSTORE_ASYNC_PUSH", "0") == "1"
        self._engine = None
        # compiled row_sparse push path (embedding/engine.py,
        # docs/EMBEDDING.md); shares the bucketing toggle — both are
        # "the compiled hot path" from the operator's point of view
        self._sparse_engine = None
        # key -> (lo, hi, vocab) for embedding tables whose stored value
        # is THIS RANK'S row slab of a pod-partitioned (vocab, dim)
        # table (ShardedEmbedding.attach_to_kvstore, docs/EMBEDDING.md);
        # such keys have no full local copy to pull or eager-update
        self._partitioned = {}

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate values per key (reference KVStoreLocal::PushImpl
        kvstore_local.h:168 → Comm::Reduce). When a compression config is
        set, each device gradient goes through quantize→dequantize with
        per-key error-feedback residual, matching gradient_compression.h.

        Eligible dense pushes take the compiled bucketed hot path
        (kvstore_fused.py): same-dtype gradients flatten into size-capped
        buckets and each bucket runs one jitted compress→reduce→update
        computation. ``priority`` (an int, or a per-key list for batched
        calls) orders bucket dispatch, highest first. With async push
        enabled (``set_async_push``/``MXNET_KVSTORE_ASYNC_PUSH=1``) work
        stays enqueued until a ``pull``/``barrier``/state-save sync point,
        letting XLA overlap it with remaining backward compute."""
        keys, values = _key_value(key, value)
        if isinstance(priority, (list, tuple)):
            if len(priority) != len(keys):
                raise MXNetError(
                    "push: %d priorities for %d keys"
                    % (len(priority), len(keys)))
            prios = list(priority)
        else:
            prios = [priority] * len(keys)
        from .ndarray.sparse import RowSparseNDArray
        with _telemetry.tracing.span("kvstore.push", keys=len(keys)):
            eng = self._get_engine()
            mode = eng._updater_mode() if eng is not None else False
            for k, vlist, prio in zip(keys, values, prios):
                if any(isinstance(v, RowSparseNDArray) for v in vlist):
                    # row_sparse gradients get their own compiled path
                    # (one dedup->compress->reduce->apply program per
                    # table); ineligible pushes fall back eager under a
                    # NARROW reason slug — "unsupported optimizer" and
                    # "ineligible dtype" warn separately
                    seng = self._get_sparse_engine()
                    sreason = seng.ineligible_reason(k, vlist) \
                        if seng is not None else None
                    if seng is not None and sreason is None:
                        seng.push(k, vlist, prio)
                    else:
                        if k in self._partitioned:
                            # a row slab cannot take the eager per-key
                            # path — there is no full local table to
                            # reduce into
                            raise MXNetError(
                                "push: key %r is row-partitioned across "
                                "hosts and must take the compiled sparse "
                                "path (blocked: %s); use an optimizer "
                                "with a fused sparse signature or set "
                                "MXNET_EMBED_PARTITION=0"
                                % (k, sreason or "bucketing disabled"))
                        if seng is not None:
                            _note_fallback(sreason, detail="key %r" % (k,))
                        self._push_one(k, vlist)
                    continue
                reason = eng.ineligible_reason(k, vlist, mode) \
                    if eng is not None else None
                if eng is not None and reason is None:
                    eng.enqueue(k, vlist, prio)
                else:
                    if eng is not None:
                        _note_fallback(reason, detail="key %r" % (k,))
                    self._push_one(k, vlist)
            if eng is not None and not self._async_push:
                eng.flush()

    def _push_one(self, k, vlist):
        """Eager per-key push (the reference shape; also the fallback for
        sparse values, custom updaters, and non-fusable optimizers)."""
        from .ndarray.sparse import RowSparseNDArray, _coalesce_rsp
        all_rsp = all(isinstance(v, RowSparseNDArray) for v in vlist)
        if self._compression is not None and not all_rsp:
            vlist = [self._compress(k, i, v) for i, v in enumerate(vlist)]
        reduced = self._local_reduce(vlist)
        if isinstance(reduced, RowSparseNDArray):
            if len(vlist) == 1:
                # single-stream pushes skip _local_reduce's coalesce;
                # duplicate indices MUST merge before the lazy updates —
                # their set-semantics row scatter would otherwise keep
                # only the last duplicate's contribution
                reduced = _coalesce_rsp(reduced._sp_data,
                                        reduced._sp_indices,
                                        reduced.shape, reduced.context)
            if self._compression is not None:
                reduced = self._compress_rsp(k, reduced)
        if self._updater is not None:
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            self._updater(_updater_key(k), reduced, self._store[k])
        else:
            self._store[k] = reduced.copy()

    def _get_engine(self):
        if not self._bucketed:
            return None
        if self._engine is None:
            from .kvstore_fused import FusedBucketEngine
            self._engine = FusedBucketEngine(self)
        return self._engine

    def _get_sparse_engine(self):
        if not self._bucketed:
            return None
        if self._sparse_engine is None:
            from .embedding.engine import SparseApplyEngine
            self._sparse_engine = SparseApplyEngine(
                self, cross_host=self._sparse_cross_host())
        return self._sparse_engine

    def _sparse_cross_host(self):
        """Whether the sparse engine must reduce across processes (the
        collective store overrides to True)."""
        return False

    def _flush_pending(self):
        if self._engine is not None:
            self._engine.flush()
            # the tpu engine's overlapped host transport applies buckets
            # on a pipeline thread; every sync point must see them land
            # before reading weights/state (docs/KVSTORE.md)
            self._engine.synchronize()

    def _sync_engine(self):
        """Flush pending buckets under the CURRENT mode, then spill flat
        error-feedback residuals back to the per-key dict. Every entry
        point that changes push routing (bucketing toggle, updater,
        compression config) must call this FIRST — in this order — or
        the engine dispatches stale-mode buckets / strands residuals.
        The sparse engine dispatches eagerly (nothing pending) but owns
        per-table residuals the same way; spill those too."""
        self._flush_pending()
        if self._engine is not None:
            self._engine.spill_residuals()
        if self._sparse_engine is not None:
            self._sparse_engine.spill_residuals()

    def set_bucketing(self, enabled):
        """Toggle the compiled bucketed hot path (docs/KVSTORE.md);
        pending async pushes are flushed first and flat error-feedback
        residuals spill back to the per-key dict."""
        self._sync_engine()
        self._bucketed = bool(enabled)

    def set_async_push(self, enabled):
        """Defer bucket dispatch until the next sync point (pull/barrier/
        state save) so pushes enqueue without blocking backward."""
        if not enabled:
            self._flush_pending()
        self._async_push = bool(enabled)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _key_value(key, out)
        with _telemetry.tracing.span("kvstore.pull", keys=len(keys)):
            self._flush_pending()
            for k, olist in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError("key %s not initialized" % k)
                if k in self._partitioned:
                    raise MXNetError(
                        "pull: key %r is row-partitioned across hosts — "
                        "no rank holds the full table; read rows through "
                        "the partitioned lookup (ShardedEmbedding "
                        "forward) or checkpoint via "
                        "embedding.checkpoint.save_tables" % (k,))
                src = self._store[k]
                for o in olist:
                    o._set_data(src._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows as row_sparse arrays (reference
        KVStore::PullRowSparse, kvstore_local.h PullRowSparseImpl).
        ``row_ids`` pairs with the flattened ``out`` list (one NDArray of
        ids per output, or a single NDArray shared by all outputs — the
        reference's semantics). With no ``row_ids`` (or a dense ``out``)
        this is a full dense pull."""
        if row_ids is None:
            self.pull(key, out=out, priority=priority)
            return
        self._flush_pending()
        from .ndarray.sparse import RowSparseNDArray
        keys, outs = _key_value(key, out)
        n_out = sum(len(olist) for olist in outs)
        if isinstance(row_ids, NDArray):
            rid_list = [row_ids] * n_out
        elif isinstance(row_ids, (list, tuple)):
            if not all(isinstance(r, NDArray) for r in row_ids):
                raise TypeError("row_ids must be an NDArray or a list of "
                                "NDArrays (one per out array)")
            if len(row_ids) != n_out:
                raise MXNetError(
                    "row_sparse_pull: %d row_ids for %d out arrays"
                    % (len(row_ids), n_out))
            rid_list = list(row_ids)
        else:
            raise TypeError("row_ids must be an NDArray or a list of "
                            "NDArrays")
        i = 0
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            if k in self._partitioned:
                raise MXNetError(
                    "row_sparse_pull: key %r is row-partitioned across "
                    "hosts; read rows through the partitioned lookup "
                    "(ShardedEmbedding forward) instead" % (k,))
            src = self._store[k]
            for o in olist:
                rids = rid_list[i]
                i += 1
                rid_host = rids.asnumpy().reshape(-1).astype(_np.int64)
                if rid_host.size and (rid_host.min() < 0
                                      or rid_host.max() >= src.shape[0]):
                    # a silent device gather would CLAMP out-of-range ids
                    # onto row 0 / row V-1 and hand back the wrong rows
                    raise MXNetError(
                        "row_sparse_pull: row_ids out of range [0, %d)"
                        % src.shape[0])
                if isinstance(o, RowSparseNDArray):
                    if tuple(o.shape) != tuple(src.shape):
                        raise MXNetError(
                            "row_sparse_pull: out shape %s != stored %s"
                            % (o.shape, src.shape))
                    # duplicates dedup; int32 on device (sparse.py
                    # contract); empty row_ids -> a valid empty rsp
                    rows = jnp.asarray(
                        _np.unique(rid_host).astype(_np.int32))
                    o._sp_data = src._data[rows]
                    o._sp_indices = rows
                    o._dense_cache = None
                else:
                    o._set_data(src._data)

    def set_updater(self, updater):
        self._sync_engine()
        self._updater = updater

    def set_optimizer(self, optimizer):
        self.set_updater(opt.get_updater(optimizer))

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression (reference kvstore.py:392)."""
        self._sync_engine()
        ctype = compression_params.get("type", "2bit")
        if ctype not in ("2bit",):
            raise MXNetError("unsupported compression type %s" % ctype)
        from .parallel.compression import TwoBitCompressor
        self._compression = TwoBitCompressor(
            threshold=float(compression_params.get("threshold", 0.5)))

    @staticmethod
    def _local_reduce(vlist):
        """Sum a per-device value list (the Comm::Reduce analog). An
        all-row_sparse list reduces row-sparse (coalescing indices) so
        lazy optimizer semantics don't depend on device count."""
        if len(vlist) == 1:
            return vlist[0]
        from .ndarray.sparse import RowSparseNDArray, _coalesce_rsp
        if all(isinstance(v, RowSparseNDArray) for v in vlist):
            # concatenate all device components, coalesce once (one host
            # sync per push, not one per device pair)
            dat = jnp.concatenate([v._sp_data for v in vlist])
            idx = jnp.concatenate([v._sp_indices for v in vlist])
            return _coalesce_rsp(dat, idx, vlist[0].shape, vlist[0].context)
        acc = vlist[0]._data
        for v in vlist[1:]:
            acc = acc + v._data
        return NDArray(acc, vlist[0].context)

    def _get_residual(self, res_key, like):
        # error-feedback residuals always live in f32 — the
        # master-gradient view — so 2-bit semantics are identical for
        # f32 and low-precision (bf16/f16) gradients, and the eager
        # path stays the bit-level parity oracle for the fused programs
        residual = self._compression_residuals.get(res_key)
        if residual is None:
            residual = zeros(like.shape, like.context, "float32")
            self._compression_residuals[res_key] = residual
        return residual

    def _compress(self, key, dev_idx, grad):
        residual = self._get_residual((key, dev_idx), grad)
        out, new_residual = self._compression.compress_decompress(
            grad._data.astype(jnp.float32), residual._data)
        residual._set_data(new_residual)
        return NDArray(out, grad.context)

    def _compress_rsp(self, key, grad):
        """Row-wise 2-bit compression for a COALESCED row_sparse grad:
        quantize only the touched rows against a table-shaped residual
        keyed ``(key, 'rsp')`` — per process, not per device (the wire
        the compression exists for is the cross-host hop). Same op
        sequence as the compiled sparse program (embedding/engine.py),
        which makes this the bit-for-bit parity oracle for it; untouched
        rows' residuals are carried, not re-emitted — the documented
        semantic difference from dense 2-bit (docs/EMBEDDING.md)."""
        from .ndarray.sparse import RowSparseNDArray
        from .kvstore_fused import two_bit_quantize
        residual = self._get_residual((key, "rsp"), grad)
        rows = grad._sp_indices
        res_rows = residual._data[rows]
        q, new_rows = two_bit_quantize(
            res_rows, grad._sp_data.astype(jnp.float32),
            self._compression.threshold)
        residual._set_data(residual._data.at[rows].set(new_rows))
        return RowSparseNDArray(q, rows, grad.shape, grad.context)

    def barrier(self):
        self._flush_pending()

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Liveness query (reference kvstore.h:341); single-process → 0."""
        return 0

    @property
    def is_recovery(self):
        return False

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        self._flush_pending()
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        self._flush_pending()
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value):
    """Normalize (key, value) into (list_of_keys, list_of_value_lists)."""
    single = isinstance(key, (str, int))
    if single:
        key = [key]
        value = [value]
    else:
        key = list(key)
        if value is None:
            value = [None] * len(key)
    out_vals = []
    for k, v in zip(key, value):
        if v is None:
            out_vals.append(None)
        elif isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return key, out_vals
