"""Network visualization: print_summary and plot_network.

Reference parity: python/mxnet/visualization.py (print_summary:47 — the
Keras-style layer table with shapes and parameter counts;
plot_network:196 — graphviz digraph). plot_network returns a
``graphviz.Digraph`` when graphviz is importable and otherwise emits DOT
text to a file (this image has no graphviz renderer; the DOT source is
the portable artifact either way).
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _node_info(symbol, shape):
    """Per-node (name, op, out_shape, params, inputs) from the DAG."""
    interior = {}
    if shape:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        args = symbol.list_arguments()
        auxs = symbol.list_auxiliary_states()
        arg_shape = dict(zip(args, arg_shapes))
        arg_shape.update(zip(auxs, aux_shapes))
        # per-node output shapes via the internals trick: eval each node
        interior = _interior_shapes(symbol, shape)
    else:
        arg_shape = {}
    rows = []
    for node in symbol._topo():
        if node.is_var:
            continue
        in_names = [inp.name for inp, _ in node.inputs]
        data_inputs = set(shape or {})
        params = 0
        for inp, _ in node.inputs:
            if inp.is_var and inp.name not in data_inputs \
                    and not inp.name.endswith("_label") \
                    and inp.name in arg_shape and arg_shape[inp.name]:
                n = 1
                for s in arg_shape[inp.name]:
                    n *= s
                params += n
        rows.append((node.name, node.op.name,
                     interior.get(node.output_name(0)), params,
                     [n for n in in_names
                      if not (n.endswith("_weight") or n.endswith("_bias")
                              or n.endswith("_gamma") or n.endswith("_beta")
                              or n.endswith("_moving_mean")
                              or n.endswith("_moving_var"))]))
    return rows


def _interior_shapes(symbol, shape):
    """Shapes of every node output, by tap name (reference: the
    get_internals().infer_shape trick)."""
    internals = symbol.get_internals()
    try:
        _, out_shapes, _ = internals.infer_shape_partial(**shape)
    except MXNetError:
        return {}
    return {name: tuple(s) for name, s in
            zip(internals.list_outputs(), out_shapes) if s is not None}


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a Keras-style summary table (reference visualization.py:47).
    ``shape``: dict of input shapes, e.g. {'data': (1, 3, 224, 224)}."""
    rows = _node_info(symbol, shape)
    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for i, f in enumerate(fields):
            line += str(f)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(headers)
    print("=" * line_length)
    total = 0
    for name, op, out_shape, params, prev in rows:
        shape_str = str(out_shape) if out_shape else ""
        print_row(["%s(%s)" % (name, op), shape_str, params,
                   ",".join(prev)])
        total += params
        print("_" * line_length)
    print("Total params: {:,}".format(total))
    print("_" * line_length)
    return total


_OP_STYLE = {
    "FullyConnected": "#fb8072", "Convolution": "#fb8072",
    "Deconvolution": "#fb8072", "BatchNorm": "#bebada",
    "Activation": "#ffffb3", "LeakyReLU": "#ffffb3",
    "Pooling": "#80b1d3", "Concat": "#fdb462", "Flatten": "#fdb462",
    "Reshape": "#fdb462", "Softmax": "#fccde5",
    "SoftmaxOutput": "#fccde5",
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the symbol (reference
    visualization.py:196). Returns a graphviz.Digraph if the graphviz
    package is available; otherwise writes '<title>.dot' DOT source and
    returns its path."""
    interior = _interior_shapes(symbol, shape) if shape else {}
    attrs = {"shape": "box", "fixedsize": "true", "width": "1.3",
             "height": "0.8034", "style": "filled"}
    attrs.update(node_attrs or {})

    nodes = []
    edges = []
    hidden_suffixes = ("_weight", "_bias", "_gamma", "_beta",
                       "_moving_mean", "_moving_var")
    for node in symbol._topo():
        if node.is_var:
            if hide_weights and node.name.endswith(hidden_suffixes):
                continue
            nodes.append((node.name, node.name, "#8dd3c7"))
            continue
        label = node.op.name
        if node.op.name in ("Convolution", "Pooling"):
            k = node.attrs.get("kernel")
            s = node.attrs.get("stride") or ""
            label = "%s\n%s/%s" % (node.op.name, k, s)
        elif node.op.name == "FullyConnected":
            label = "FullyConnected\n%s" % node.attrs.get("num_hidden")
        elif node.op.name == "Activation":
            label = "Activation\n%s" % node.attrs.get("act_type")
        color = _OP_STYLE.get(node.op.name, "#b3de69")
        nodes.append((node.name, label, color))
        for inp, oi in node.inputs:
            if inp.is_var and hide_weights and \
                    inp.name.endswith(hidden_suffixes):
                continue
            elabel = ""
            if interior and not inp.is_var:
                s = interior.get(inp.output_name(oi))
                if s:
                    elabel = "x".join(str(x) for x in s[1:])
            edges.append((inp.name, node.name, elabel))

    try:
        from graphviz import Digraph
    except ImportError:
        Digraph = None

    if Digraph is not None:
        dot = Digraph(name=title, format=save_format)
        for name, label, color in nodes:
            a = dict(attrs)
            a["fillcolor"] = color
            dot.node(name=name, label=label, **a)
        for src, dst, elabel in edges:
            dot.edge(src, dst, label=elabel,
                     **{"dir": "back", "arrowtail": "open"})
        return dot

    lines = ["digraph %s {" % json.dumps(title)]
    for name, label, color in nodes:
        lines.append('  %s [label=%s, shape=box, style=filled, '
                     'fillcolor="%s"];' % (json.dumps(name),
                                           json.dumps(label), color))
    for src, dst, elabel in edges:
        lines.append('  %s -> %s [label=%s, dir=back, arrowtail=open];'
                     % (json.dumps(src), json.dumps(dst),
                        json.dumps(elabel)))
    lines.append("}")
    path = "%s.dot" % title
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path
