"""mx.contrib — experimental subsystems (reference python/mxnet/contrib/).

Present: ``quantization`` (INT8 post-training quantization),
``autograd`` (legacy pre-Gluon autograd surface), ``io``
(DataLoaderIter), ``tensorboard`` (metric logging callback), ``text``
(Vocabulary + token embeddings), ``ndarray``/``symbol`` (contrib op
namespaces, same objects as mx.nd.contrib / mx.sym.contrib), ``onnx``
(entry points gated on the third-party onnx package, as in the
reference).
"""
from . import quantization  # noqa: F401
from . import autograd      # noqa: F401
from . import io            # noqa: F401
from . import tensorboard   # noqa: F401
from . import text          # noqa: F401
from . import ndarray       # noqa: F401
from . import symbol        # noqa: F401
from . import onnx          # noqa: F401
