"""mx.contrib — experimental subsystems (reference python/mxnet/contrib/).

Present: ``quantization`` (INT8 post-training quantization). Control
flow lives in ``mx.sym.contrib`` / ``mx.nd.contrib``; ONNX
import/export is not implemented (the reference's contrib.onnx targets
a serialization ecosystem outside this rebuild's scope).
"""
from . import quantization  # noqa: F401
