"""Contrib IO: run a Gluon DataLoader through the symbolic DataIter
protocol.

Reference parity: python/mxnet/contrib/io.py (DataLoaderIter) — lets
``Module.fit`` consume a ``gluon.data.DataLoader``.
"""
from __future__ import annotations

import numpy as _np

from ..io.io import DataIter, DataBatch, DataDesc
from ..ndarray import NDArray, array

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Iterate a ``gluon.data.DataLoader`` as ``DataBatch``es of
    (data, label) pairs (ref contrib/io.py DataLoaderIter)."""

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._dtype = dtype
        self._data_name = data_name
        self._label_name = label_name
        try:
            first = next(self._iter)
        except StopIteration:
            raise ValueError("DataLoader is empty") from None
        data, label = self._split(first)
        self._provide_data = [DataDesc(data_name, data.shape, dtype)]
        self._provide_label = [DataDesc(label_name, label.shape, dtype)]
        self._first = (data, label)

    def _split(self, batch):
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            data, label = batch
        else:
            raise ValueError("DataLoader must yield (data, label) pairs.")

        def to_nd(x):
            if isinstance(x, NDArray):
                return x.astype(self._dtype)
            return array(_np.asarray(x), dtype=self._dtype)

        return to_nd(data), to_nd(label)

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self._iter = iter(self._loader)
        self._first = None

    def next(self):
        if self._first is not None:
            data, label = self._first
            self._first = None
        else:
            try:
                data, label = self._split(next(self._iter))
            except StopIteration:
                raise StopIteration
        pad = 0
        batch_size = self._provide_data[0].shape[0]
        actual = data.shape[0]
        if actual < batch_size:
            # Pad the trailing partial batch up to batch_size (ref
            # contrib/io.py getdata/getpad): repeat the last row and
            # report pad so Module slices the padded tail off again.
            pad = batch_size - actual
            data = self._pad_to(data, batch_size)
            label = self._pad_to(label, batch_size)
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self._provide_data,
                         provide_label=self._provide_label)

    @staticmethod
    def _pad_to(arr, batch_size):
        # fill with repeats of real rows (like NDArrayIter's wrap-around)
        # so the padded tail never injects fabricated zero-label samples
        # into training gradients — fit does not slice pad off
        np_arr = arr.asnumpy()
        n = np_arr.shape[0]
        idx = _np.arange(batch_size) % n
        return array(np_arr[idx], dtype=str(np_arr.dtype))
