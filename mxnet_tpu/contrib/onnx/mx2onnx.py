"""Symbol graph -> ONNX ModelProto translation.

Reference parity: python/mxnet/contrib/onnx/mx2onnx/export_model.py +
_op_translations.py (3.8k LoC of per-op converters). This build vendors
a minimal ONNX IR protobuf (onnx_proto/onnx.proto — field-compatible
with the upstream schema, so the emitted files load in stock
onnx/onnxruntime) instead of depending on the uninstallable ``onnx``
package, and translates the model-zoo op subset: Convolution,
BatchNorm, FullyConnected, Activation, LeakyReLU, Pooling, Flatten,
Reshape, Concat, Dropout, Cast, SoftmaxOutput/softmax, LayerNorm,
elementwise add/sub/mul, and broadcast_add.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import onnx_pb2 as O

_OPSET = 13

_DTYPE_TO_ONNX = {"float32": O.TensorProto.FLOAT,
                  "float64": O.TensorProto.DOUBLE,
                  "float16": O.TensorProto.FLOAT16,
                  "bfloat16": O.TensorProto.BFLOAT16,
                  "uint8": O.TensorProto.UINT8,
                  "int8": O.TensorProto.INT8,
                  "int32": O.TensorProto.INT32,
                  "int64": O.TensorProto.INT64,
                  "bool": O.TensorProto.BOOL}


def _attr(name, value):
    a = O.AttributeProto(name=name)
    if isinstance(value, bool):
        a.type = O.AttributeProto.INT
        a.i = int(value)
    elif isinstance(value, int):
        a.type = O.AttributeProto.INT
        a.i = value
    elif isinstance(value, float):
        a.type = O.AttributeProto.FLOAT
        a.f = value
    elif isinstance(value, str):
        a.type = O.AttributeProto.STRING
        a.s = value.encode()
    elif isinstance(value, (tuple, list)):
        if value and isinstance(value[0], float):
            a.type = O.AttributeProto.FLOATS
            a.floats.extend(value)
        else:
            a.type = O.AttributeProto.INTS
            a.ints.extend(int(v) for v in value)
    else:
        raise MXNetError("onnx export: bad attribute %s=%r" % (name, value))
    return a


def _node(op_type, inputs, outputs, name, **attrs):
    n = O.NodeProto(op_type=op_type, name=name)
    n.input.extend(inputs)
    n.output.extend(outputs)
    for k, v in attrs.items():
        if v is None:
            continue
        n.attribute.append(_attr(k, v))
    return n


def _tensor(name, arr):
    arr = _np.ascontiguousarray(arr)
    t = O.TensorProto(name=name)
    t.dims.extend(arr.shape)
    dt = str(arr.dtype)
    if dt not in _DTYPE_TO_ONNX:
        arr = arr.astype("float32")
        dt = "float32"
    t.data_type = _DTYPE_TO_ONNX[dt]
    t.raw_data = arr.tobytes()
    return t


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v) or (1,) * n
    return (int(v),) * n


class _Ctx:
    """Per-export state: emitted nodes + fresh-name helper."""

    def __init__(self):
        self.nodes = []
        self._uid = 0

    def fresh(self, base):
        self._uid += 1
        return "%s__%d" % (base, self._uid)

    def add(self, *nodes):
        self.nodes.extend(nodes)


# ---------------------------------------------------------------------
# per-op converters: (node, in_names, out_name, ctx) -> None
# ---------------------------------------------------------------------
def _c_convolution(n, ins, out, ctx):
    a = n.attrs
    kernel = _pair(a["kernel"], len(a["kernel"]))
    nd = len(kernel)
    stride = _pair(a.get("stride") or (1,) * nd, nd)
    pad = _pair(a.get("pad") or (0,) * nd, nd)
    dilate = _pair(a.get("dilate") or (1,) * nd, nd)
    layout = a.get("layout")
    if layout and str(layout).endswith("C"):
        raise MXNetError("onnx export: channel-last Convolution not "
                         "supported (ONNX Conv is NCHW); build the "
                         "symbol with layout='NCHW' for export")
    ctx.add(_node("Conv", ins, [out], n.name,
                  kernel_shape=kernel, strides=stride,
                  pads=list(pad) + list(pad), dilations=dilate,
                  group=int(a.get("num_group", 1))))


def _c_batchnorm(n, ins, out, ctx):
    a = n.attrs
    # inputs: data gamma beta moving_mean moving_var (already this order)
    ctx.add(_node("BatchNormalization", ins, [out], n.name,
                  epsilon=float(a.get("eps", 1e-3)),
                  momentum=float(a.get("momentum", 0.9))))


def _c_fully_connected(n, ins, out, ctx):
    a = n.attrs
    data, weight = ins[0], ins[1]
    bias = ins[2] if len(ins) > 2 and not a.get("no_bias") else None
    if a.get("flatten", True):
        flat = ctx.fresh(n.name + "_flat")
        ctx.add(_node("Flatten", [data], [flat], flat, axis=1))
        data = flat
        gemm_in = [data, weight] + ([bias] if bias else [])
        ctx.add(_node("Gemm", gemm_in, [out], n.name, alpha=1.0, beta=1.0,
                      transA=0, transB=1))
    else:
        # (…, in) x (out, in)^T via MatMul on transposed weight
        wt = ctx.fresh(n.name + "_wT")
        ctx.add(_node("Transpose", [weight], [wt], wt, perm=[1, 0]))
        mm = ctx.fresh(n.name + "_mm") if bias else out
        ctx.add(_node("MatMul", [data, wt], [mm], n.name + "_matmul"))
        if bias:
            ctx.add(_node("Add", [mm, bias], [out], n.name))


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _c_activation(n, ins, out, ctx):
    ctx.add(_node(_ACT[n.attrs["act_type"]], ins, [out], n.name))


def _c_leaky_relu(n, ins, out, ctx):
    a = n.attrs
    act = a.get("act_type", "leaky")
    if act == "leaky":
        ctx.add(_node("LeakyRelu", ins[:1], [out], n.name,
                      alpha=float(a.get("slope", 0.25))))
    elif act == "elu":
        ctx.add(_node("Elu", ins[:1], [out], n.name,
                      alpha=float(a.get("slope", 0.25))))
    elif act == "prelu":
        ctx.add(_node("PRelu", ins[:2], [out], n.name))
    elif act == "selu":
        ctx.add(_node("Selu", ins[:1], [out], n.name))
    elif act == "gelu":
        # Gelu is a standard op only from opset 20
        raise MXNetError("onnx export: gelu not supported at opset %d"
                         % _OPSET)
    else:
        raise MXNetError("onnx export: LeakyReLU act_type=%s" % act)


def _c_pooling(n, ins, out, ctx):
    a = n.attrs
    layout = a.get("layout")
    if layout and str(layout).endswith("C"):
        raise MXNetError("onnx export: channel-last Pooling not supported")
    ptype = a.get("pool_type", "max")
    if a.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError("onnx export: global %s pooling" % ptype)
        ctx.add(_node(op, ins, [out], n.name))
        return
    kernel = _pair(a["kernel"], len(a["kernel"]))
    nd = len(kernel)
    stride = _pair(a.get("stride") or (1,) * nd, nd)
    pad = _pair(a.get("pad") or (0,) * nd, nd)
    kw = dict(kernel_shape=kernel, strides=stride,
              pads=list(pad) + list(pad))
    if ptype == "max":
        ctx.add(_node("MaxPool", ins, [out], n.name, **kw))
    elif ptype == "avg":
        kw["count_include_pad"] = 1 if a.get("count_include_pad", True) else 0
        ctx.add(_node("AveragePool", ins, [out], n.name, **kw))
    else:
        raise MXNetError("onnx export: pool_type=%s" % ptype)


def _c_flatten(n, ins, out, ctx):
    ctx.add(_node("Flatten", ins, [out], n.name, axis=1))


def _c_reshape(n, ins, out, ctx):
    shape = [int(s) for s in n.attrs.get("shape", ())]
    shp_name = ctx.fresh(n.name + "_shape")
    const = _node("Constant", [], [shp_name], shp_name)
    a = O.AttributeProto(name="value", type=O.AttributeProto.TENSOR)
    a.t.CopyFrom(_tensor(shp_name + "_v",
                         _np.asarray(shape, dtype="int64")))
    const.attribute.append(a)
    ctx.add(const)
    ctx.add(_node("Reshape", [ins[0], shp_name], [out], n.name))


def _c_concat(n, ins, out, ctx):
    ctx.add(_node("Concat", ins, [out], n.name,
                  axis=int(n.attrs.get("dim", 1))))


def _c_dropout(n, ins, out, ctx):
    ctx.add(_node("Dropout", ins[:1], [out], n.name))


def _c_cast(n, ins, out, ctx):
    ctx.add(_node("Cast", ins, [out], n.name,
                  to=int(_DTYPE_TO_ONNX[str(n.attrs["dtype"])])))


def _c_softmax_output(n, ins, out, ctx):
    # inference semantics: softmax over the trailing axis (the label
    # input is dropped, like the reference converter)
    ctx.add(_node("Softmax", ins[:1], [out], n.name, axis=-1))


def _c_softmax(n, ins, out, ctx):
    ctx.add(_node("Softmax", ins[:1], [out], n.name,
                  axis=int(n.attrs.get("axis", -1))))


def _c_add(n, ins, out, ctx):
    ctx.add(_node("Add", ins, [out], n.name))


def _c_sub(n, ins, out, ctx):
    ctx.add(_node("Sub", ins, [out], n.name))


def _c_mul(n, ins, out, ctx):
    ctx.add(_node("Mul", ins, [out], n.name))


def _c_layer_norm(n, ins, out, ctx):
    ctx.add(_node("LayerNormalization", ins, [out], n.name,
                  axis=int(n.attrs.get("axis", -1)),
                  epsilon=float(n.attrs.get("eps", 1e-5))))


_CONVERTERS = {
    "Convolution": _c_convolution,
    "BatchNorm": _c_batchnorm,
    "FullyConnected": _c_fully_connected,
    "Activation": _c_activation,
    "LeakyReLU": _c_leaky_relu,
    "Pooling": _c_pooling,
    "Flatten": _c_flatten,
    "Reshape": _c_reshape,
    "Concat": _c_concat,
    "Dropout": _c_dropout,
    "Cast": _c_cast,
    "SoftmaxOutput": _c_softmax_output,
    "softmax": _c_softmax,
    "elemwise_add": _c_add,
    "_plus": _c_add,
    "_Plus": _c_add,
    "broadcast_add": _c_add,
    "elemwise_sub": _c_sub,
    "broadcast_sub": _c_sub,
    "elemwise_mul": _c_mul,
    "broadcast_mul": _c_mul,
    "LayerNorm": _c_layer_norm,
}


def export_model(sym, params, input_shapes, input_dtype="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Translate (symbol, params) to an ONNX file; returns the path.
    ``input_shapes`` is a dict name -> shape for the data inputs (label
    inputs are dropped, reference mx2onnx behavior). ``params`` may mix
    ``arg:``/``aux:`` prefixed keys (checkpoint layout) or be plain."""
    from ...ndarray.ndarray import NDArray

    flat_params = {}
    for k, v in (params or {}).items():
        name = k.split(":", 1)[1] if ":" in k else k
        flat_params[name] = v.asnumpy() if isinstance(v, NDArray) else \
            _np.asarray(v)

    topo = sym._topo()
    entries = list(sym._entries)
    label_names = {n for n in sym.list_arguments()
                   if n.endswith("_label") or n == "label"}

    ctx = _Ctx()
    names = {}           # (id(node), out_idx) -> onnx value name
    graph = O.GraphProto(name="mxnet_tpu")
    used_inputs = []

    for node in topo:
        if node.is_var:
            names[(id(node), 0)] = node.name
            if node.name in flat_params:
                graph.initializer.append(
                    _tensor(node.name, flat_params[node.name]))
            elif node.name in input_shapes:
                vi = graph.input.add()
                vi.name = node.name
                vi.type.tensor_type.elem_type = _DTYPE_TO_ONNX[input_dtype]
                for d in input_shapes[node.name]:
                    vi.type.tensor_type.shape.dim.add().dim_value = int(d)
                used_inputs.append(node.name)
            elif node.name in label_names:
                names[(id(node), 0)] = None   # dropped (inference graph)
            else:
                raise MXNetError(
                    "onnx export: variable '%s' has no param value and no "
                    "input shape" % node.name)
            continue
        conv = _CONVERTERS.get(node.op.name)
        if conv is None:
            raise MXNetError(
                "onnx export: operator '%s' (node '%s') has no converter; "
                "supported: %s"
                % (node.op.name, node.name, sorted(_CONVERTERS)))
        ins = [names[(id(inp), oi)] for inp, oi in node.inputs]
        ins = [i for i in ins if i is not None]
        out = node.output_name(0) if node.visible_out_count() == 1 \
            else node.name + "_output0"
        conv(node, ins, out, ctx)
        for i in range(node.out_count()):
            names[(id(node), i)] = out if i == 0 else \
                node.name + "_output%d" % i

    graph.node.extend(ctx.nodes)
    for head, oi in entries:
        out_name = names[(id(head), oi)]
        vo = graph.output.add()
        vo.name = out_name
        vo.type.tensor_type.elem_type = _DTYPE_TO_ONNX[input_dtype]

    model = O.ModelProto(ir_version=7, producer_name="mxnet_tpu",
                         producer_version="0.3")
    ops = model.opset_import.add()
    ops.domain = ""
    ops.version = _OPSET
    model.graph.CopyFrom(graph)
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    if verbose:
        print("exported %d nodes, %d initializers -> %s"
              % (len(graph.node), len(graph.initializer), onnx_file_path))
    return onnx_file_path
