"""ONNX ModelProto -> Symbol graph translation.

Reference parity: python/mxnet/contrib/onnx/onnx2mx/import_model.py +
import_onnx.py + _op_translations.py. Reads the vendored minimal ONNX
IR protobuf (field-compatible with upstream onnx.proto3, so files
produced by stock onnx/pytorch exporters parse — unknown fields are
skipped by protobuf). Covers the inverse of the mx2onnx converter set:
Conv, BatchNormalization, Gemm, MatMul, Add/Sub/Mul, Relu/Sigmoid/
Tanh/Softplus/Softsign/LeakyRelu/Elu/PRelu, MaxPool/AveragePool/
Global*Pool, Flatten, Reshape, Concat, Dropout, Cast, Softmax,
LayerNormalization, Constant.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import onnx_pb2 as O

_ONNX_TO_DTYPE = {O.TensorProto.FLOAT: "float32",
                  O.TensorProto.DOUBLE: "float64",
                  O.TensorProto.FLOAT16: "float16",
                  O.TensorProto.BFLOAT16: "bfloat16",
                  O.TensorProto.UINT8: "uint8",
                  O.TensorProto.INT8: "int8",
                  O.TensorProto.INT32: "int32",
                  O.TensorProto.INT64: "int64",
                  O.TensorProto.BOOL: "bool"}


def _tensor_to_np(t):
    dtype = _ONNX_TO_DTYPE.get(t.data_type)
    if dtype is None:
        raise MXNetError("onnx import: unsupported tensor dtype %d"
                         % t.data_type)
    shape = tuple(t.dims)
    if t.raw_data:
        arr = _np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = _np.asarray(list(t.float_data), dtype=dtype)
    elif t.int64_data:
        arr = _np.asarray(list(t.int64_data), dtype=dtype)
    elif t.int32_data:
        arr = _np.asarray(list(t.int32_data), dtype=dtype)
    elif t.double_data:
        arr = _np.asarray(list(t.double_data), dtype=dtype)
    else:
        arr = _np.zeros(shape, dtype=dtype)
    return arr.reshape(shape).copy()


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == O.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == O.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == O.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == O.AttributeProto.INTS:
            out[a.name] = tuple(int(v) for v in a.ints)
        elif a.type == O.AttributeProto.FLOATS:
            out[a.name] = tuple(float(v) for v in a.floats)
        elif a.type == O.AttributeProto.TENSOR:
            out[a.name] = _tensor_to_np(a.t)
    return out


def _sym_pads(pads, nd):
    """ONNX pads [b0,b1,...,e0,e1,...] -> symmetric MXNet pad tuple."""
    if not pads:
        return (0,) * nd
    begin, end = pads[:nd], pads[nd:]
    if tuple(begin) != tuple(end):
        raise MXNetError("onnx import: asymmetric pads %s" % (pads,))
    return tuple(int(p) for p in begin)


class _Importer:
    def __init__(self, graph):
        from ... import symbol as sym
        self.sym = sym
        self.graph = graph
        self.env = {}          # value name -> Symbol
        self.params = {}       # param name -> np array
        for init in graph.initializer:
            self.params[init.name] = _tensor_to_np(init)
        for vi in graph.input:
            if vi.name not in self.params:
                self.env[vi.name] = sym.Variable(vi.name)
        for name in self.params:
            self.env[name] = sym.Variable(name)

    # -- converters ----------------------------------------------------
    def _conv(self, n, a):
        w_shape = self.params[n.input[1]].shape
        kernel = a.get("kernel_shape", w_shape[2:])
        nd = len(kernel)
        return self.sym.Convolution(
            *[self.env[i] for i in n.input],
            kernel=tuple(kernel), num_filter=w_shape[0],
            stride=a.get("strides", (1,) * nd),
            pad=_sym_pads(a.get("pads", ()), nd),
            dilate=a.get("dilations", (1,) * nd),
            num_group=a.get("group", 1),
            no_bias=(len(n.input) < 3), name=n.name or n.output[0])

    def _bn(self, n, a):
        return self.sym.BatchNorm(
            *[self.env[i] for i in n.input],
            eps=a.get("epsilon", 1e-5), momentum=a.get("momentum", 0.9),
            fix_gamma=False, use_global_stats=True,
            name=n.name or n.output[0])

    def _gemm(self, n, a):
        if a.get("transA") or not a.get("transB", 0):
            raise MXNetError("onnx import: Gemm with transA/transB=0")
        num_hidden = self.params[n.input[1]].shape[0]
        return self.sym.FullyConnected(
            *[self.env[i] for i in n.input], num_hidden=num_hidden,
            no_bias=(len(n.input) < 3), name=n.name or n.output[0])

    def _matmul(self, n, a):
        return self.sym.dot(self.env[n.input[0]], self.env[n.input[1]],
                            name=n.name or n.output[0])

    def _pool(self, n, a, ptype, global_pool=False):
        if global_pool:
            return self.sym.Pooling(self.env[n.input[0]], global_pool=True,
                                    kernel=(1, 1), pool_type=ptype,
                                    name=n.name or n.output[0])
        kernel = a["kernel_shape"]
        nd = len(kernel)
        return self.sym.Pooling(
            self.env[n.input[0]], kernel=tuple(kernel), pool_type=ptype,
            stride=a.get("strides", (1,) * nd),
            pad=_sym_pads(a.get("pads", ()), nd),
            count_include_pad=bool(a.get("count_include_pad", 1)),
            name=n.name or n.output[0])

    def _act(self, n, a, act_type):
        return self.sym.Activation(self.env[n.input[0]], act_type=act_type,
                                   name=n.name or n.output[0])

    def _reshape(self, n, a):
        if len(n.input) > 1:
            shape_src = n.input[1]
            if shape_src in self.params:
                shape = tuple(int(s) for s in self.params[shape_src])
                # consumed as a constant, not a runtime input
                self.params.pop(shape_src, None)
            elif shape_src in self.constants:
                shape = tuple(int(s) for s in self.constants[shape_src])
            else:
                raise MXNetError("onnx import: dynamic Reshape shape")
        else:
            shape = tuple(a.get("shape", ()))
        return self.sym.Reshape(self.env[n.input[0]], shape=shape,
                                name=n.name or n.output[0])

    def convert(self):
        sym = self.sym
        self.constants = {}
        for n in self.graph.node:
            a = _attrs(n)
            op = n.op_type
            name = n.name or n.output[0]
            if op == "Constant":
                self.constants[n.output[0]] = a["value"]
                continue
            if op == "Conv":
                out = self._conv(n, a)
            elif op == "BatchNormalization":
                out = self._bn(n, a)
            elif op == "Gemm":
                out = self._gemm(n, a)
            elif op == "MatMul":
                out = self._matmul(n, a)
            elif op == "Add":
                out = sym.broadcast_add(self.env[n.input[0]],
                                        self.env[n.input[1]], name=name)
            elif op == "Sub":
                out = sym.broadcast_sub(self.env[n.input[0]],
                                        self.env[n.input[1]], name=name)
            elif op == "Mul":
                out = sym.broadcast_mul(self.env[n.input[0]],
                                        self.env[n.input[1]], name=name)
            elif op == "Relu":
                out = self._act(n, a, "relu")
            elif op == "Sigmoid":
                out = self._act(n, a, "sigmoid")
            elif op == "Tanh":
                out = self._act(n, a, "tanh")
            elif op == "Softplus":
                out = self._act(n, a, "softrelu")
            elif op == "Softsign":
                out = self._act(n, a, "softsign")
            elif op == "LeakyRelu":
                out = sym.LeakyReLU(self.env[n.input[0]], act_type="leaky",
                                    slope=a.get("alpha", 0.01), name=name)
            elif op == "Elu":
                out = sym.LeakyReLU(self.env[n.input[0]], act_type="elu",
                                    slope=a.get("alpha", 1.0), name=name)
            elif op == "PRelu":
                out = sym.LeakyReLU(self.env[n.input[0]],
                                    self.env[n.input[1]],
                                    act_type="prelu", name=name)
            elif op == "MaxPool":
                out = self._pool(n, a, "max")
            elif op == "AveragePool":
                out = self._pool(n, a, "avg")
            elif op == "GlobalMaxPool":
                out = self._pool(n, a, "max", global_pool=True)
            elif op == "GlobalAveragePool":
                out = self._pool(n, a, "avg", global_pool=True)
            elif op == "Flatten":
                out = sym.Flatten(self.env[n.input[0]], name=name)
            elif op == "Reshape":
                out = self._reshape(n, a)
            elif op == "Concat":
                out = sym.Concat(*[self.env[i] for i in n.input],
                                 dim=a.get("axis", 1), name=name)
            elif op == "Dropout":
                out = sym.Dropout(self.env[n.input[0]],
                                  p=a.get("ratio", 0.5), name=name)
            elif op == "Cast":
                dt = _ONNX_TO_DTYPE[a["to"]]
                out = sym.Cast(self.env[n.input[0]], dtype=dt, name=name)
            elif op == "Softmax":
                out = sym.softmax(self.env[n.input[0]],
                                  axis=a.get("axis", -1), name=name)
            elif op == "LayerNormalization":
                out = sym.LayerNorm(*[self.env[i] for i in n.input],
                                    axis=a.get("axis", -1),
                                    eps=a.get("epsilon", 1e-5), name=name)
            elif op == "Identity":
                out = self.env[n.input[0]]
            else:
                raise MXNetError(
                    "onnx import: operator '%s' has no converter" % op)
            for o in n.output[:1]:
                self.env[o] = out
        outs = [self.env[vo.name] for vo in self.graph.output]
        return outs[0] if len(outs) == 1 else sym.Group(outs)


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params)
    (ref onnx2mx/import_model.py:32)."""
    from ...ndarray.ndarray import array as nd_array

    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    imp = _Importer(model.graph)
    out_sym = imp.convert()
    aux_names = set(out_sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name, arr in imp.params.items():
        (aux_params if name in aux_names else arg_params)[name] = \
            nd_array(arr)
    # drop params consumed as constants that no longer appear in the graph
    arg_names = set(out_sym.list_arguments())
    arg_params = {k: v for k, v in arg_params.items() if k in arg_names}
    return out_sym, arg_params, aux_params


def get_model_metadata(model_file):
    """Input/output names and shapes (ref onnx2mx/import_model.py:66)."""
    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    init = {t.name for t in g.initializer}

    def shapes(vis):
        out = []
        for vi in vis:
            if vi.name in init:
                continue
            dims = tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)
            out.append((vi.name, dims))
        return out

    return {"input_tensor_data": shapes(g.input),
            "output_tensor_data": shapes(g.output)}
