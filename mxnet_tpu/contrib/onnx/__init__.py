"""ONNX import/export (reference python/mxnet/contrib/onnx/).

Reference: onnx2mx/import_model.py + mx2onnx/export_model.py (a 3.8k
LoC translator pair built on the third-party ``onnx`` package). That
package is not installable here, so this build vendors a minimal ONNX
IR protobuf (onnx_proto/onnx.proto, generated bindings committed as
onnx_pb2.py) whose field numbers match the upstream schema exactly —
emitted files load in stock onnx/onnxruntime, and models serialized by
stock exporters parse here (protobuf skips the upstream fields the
subset omits). Translation covers the model-zoo operator subset; see
mx2onnx.py / onnx2mx.py for the exact lists.
"""
from __future__ import annotations

from .mx2onnx import export_model
from .onnx2mx import import_model, get_model_metadata

__all__ = ["import_model", "export_model", "get_model_metadata"]
