"""ONNX import/export (reference python/mxnet/contrib/onnx/).

Like the reference, this package requires the third-party ``onnx``
library (the reference raises ImportError from onnx2mx/mx2onnx when it
is absent — import_model docstring: "Instructions to install - ...").
``onnx`` is not installed in this environment, so the entry points
raise with the same guidance instead of exposing half-working stubs.
"""
from __future__ import annotations

__all__ = ["import_model", "export_model", "get_model_metadata"]

_MSG = ("ONNX support requires the `onnx` package, which is not "
        "installed. Instructions to install - "
        "https://github.com/onnx/onnx#installation")


def _require_onnx():
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ImportError(_MSG) from None


def import_model(model_file):
    """Load an ONNX model file into (sym, arg_params, aux_params)
    (ref onnx2mx/import_model.py)."""
    _require_onnx()
    raise NotImplementedError(
        "ONNX graph translation is not implemented in this build; the "
        "reference-format symbol.json + .params checkpoint loaders "
        "(mx.model.load_checkpoint) are the supported interchange path.")


def get_model_metadata(model_file):
    """Input/output shape metadata of an ONNX model
    (ref onnx2mx/import_model.py:66)."""
    _require_onnx()
    raise NotImplementedError(
        "ONNX graph translation is not implemented in this build.")


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a symbol+params to ONNX (ref mx2onnx/export_model.py)."""
    _require_onnx()
    raise NotImplementedError(
        "ONNX graph translation is not implemented in this build.")
