"""Text utilities: vocabulary + token embeddings.

Reference parity: python/mxnet/contrib/text/ (vocab.py, embedding.py,
utils.py).
"""
from . import utils
from . import vocab
from . import embedding
from .vocab import Vocabulary

__all__ = ["utils", "vocab", "embedding", "Vocabulary"]
