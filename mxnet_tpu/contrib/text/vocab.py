"""Text token indexing.

Reference parity: python/mxnet/contrib/text/vocab.py:30-210 (Vocabulary).
Pure Python — nothing device-specific to redesign.
"""
from __future__ import annotations

import collections

__all__ = ["Vocabulary"]


class Vocabulary(object):
    """Maps tokens to indices.

    Index 0 is the unknown token; reserved tokens follow, then counter
    keys sorted by frequency (descending), ties broken alphabetically
    (ref vocab.py:113-140). Tokens below ``min_freq`` or beyond
    ``most_freq_count`` are dropped.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("`min_freq` must be set to a positive value.")
        if reserved_tokens is not None:
            reserved = set(reserved_tokens)
            if unknown_token in reserved:
                raise ValueError("`reserved_tokens` must not contain the "
                                 "unknown token.")
            if len(reserved) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` must not contain "
                                 "duplicates.")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens is not None else None)
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        if reserved_tokens is not None:
            for tok in reserved_tokens:
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        if not isinstance(counter, collections.Counter):
            raise TypeError("`counter` must be a collections.Counter.")
        special = set(self._idx_to_token)
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        cap = len(special) + (len(counter) if most_freq_count is None
                              else most_freq_count)
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) == cap:
                break
            if token not in special:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (str) or list of tokens → index or list of indices;
        unknown tokens map to index 0 (ref vocab.py:160)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Index (int) or list of indices → token or list of tokens
        (ref vocab.py:186)."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("Token index %d in the provided `indices` "
                                 "is invalid." % i)
            out.append(self._idx_to_token[i])
        return out[0] if single else out
