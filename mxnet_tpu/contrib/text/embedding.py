"""Token embeddings.

Reference parity: python/mxnet/contrib/text/embedding.py:39-770
(_TokenEmbedding, GloVe, FastText, CustomEmbedding, CompositeEmbedding,
register/create/get_pretrained_file_names). Vectors live in an NDArray;
lookups are row gathers, so `get_vecs_by_tokens` output feeds
`mx.nd.Embedding` / `gluon.nn.Embedding` weight initialization directly.

Pretrained archives are NOT auto-downloaded here (this build has no
network egress); point `pretrained_file_path` / `embedding_root` at a
local copy instead.
"""
from __future__ import annotations

import io
import os
import warnings

from ... import ndarray as nd
from . import vocab as _vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

UNKNOWN_IDX = 0

_EMBED_REGISTRY = {}


def register(embedding_cls):
    """Register a subclass of TokenEmbedding under its lower-cased class
    name (ref embedding.py:39)."""
    _EMBED_REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Create by registered name, e.g. create('glove',
    pretrained_file_name=...) (ref embedding.py:62)."""
    key = embedding_name.lower()
    if key not in _EMBED_REGISTRY:
        raise KeyError(
            "Cannot find `embedding_name` %s. Valid embedding names: %s"
            % (embedding_name, ", ".join(sorted(_EMBED_REGISTRY))))
    return _EMBED_REGISTRY[key](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names, per embedding or all as a dict
    (ref embedding.py:89)."""
    if embedding_name is not None:
        key = embedding_name.lower()
        if key not in _EMBED_REGISTRY:
            raise KeyError("Cannot find `embedding_name` %s."
                           % embedding_name)
        return list(_EMBED_REGISTRY[key].pretrained_file_names)
    return {name: list(cls.pretrained_file_names)
            for name, cls in _EMBED_REGISTRY.items()}


class TokenEmbedding(_vocab.Vocabulary):
    """Base token embedding: a Vocabulary plus an (len, vec_len) NDArray
    of vectors (ref embedding.py:132 _TokenEmbedding)."""

    pretrained_file_names = ()

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = None
        self._idx_to_vec = None

    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        """Locate a pretrained file under ``embedding_root`` — no
        download in this environment (ref embedding.py:199 downloads
        from the embedding's URL)."""
        embedding_dir = os.path.join(
            os.path.expanduser(embedding_root), cls.__name__.lower())
        path = os.path.join(embedding_dir, pretrained_file_name)
        if not os.path.isfile(path):
            raise RuntimeError(
                "Pretrained file %s was not found under %s and automatic "
                "download is unavailable in this environment. Place the "
                "file there, or use CustomEmbedding with a local "
                "`pretrained_file_path`." % (pretrained_file_name,
                                             embedding_dir))
        return path

    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parse a text embedding file: `token<d>v1<d>v2...` per line
        (ref embedding.py:231-303: first-seen vector wins on duplicate
        tokens, 1-dim lines are treated as headers and skipped, the
        unknown token's vector comes from the file when present)."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError("`pretrained_file_path` must be a valid path "
                             "to the pre-trained token embedding file.")
        vec_len = None
        all_rows = []
        seen = set()
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f, 1):
                elems = line.rstrip().split(elem_delim)
                if len(elems) <= 1:
                    raise ValueError(
                        "At line %d: unexpected data format in %s."
                        % (line_num, pretrained_file_path))
                token, vals = elems[0], [float(x) for x in elems[1:]]
                if token == self.unknown_token and loaded_unknown_vec is None:
                    loaded_unknown_vec = vals
                    seen.add(token)
                elif token in seen:
                    warnings.warn("At line %d: duplicate embedding for "
                                  "token %s skipped." % (line_num, token))
                elif len(vals) == 1:
                    warnings.warn("At line %d: token %s with 1-dimensional "
                                  "vector %s is likely a header, skipped."
                                  % (line_num, token, vals))
                else:
                    if vec_len is None:
                        vec_len = len(vals)
                    elif len(vals) != vec_len:
                        raise ValueError(
                            "At line %d: token %s has dimension %d but "
                            "previous tokens have %d."
                            % (line_num, token, len(vals), vec_len))
                    all_rows.append(vals)
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)
                    seen.add(token)
        if vec_len is None:
            raise ValueError(
                "No valid embedding vectors found in %s: every line was a "
                "header, a duplicate, or the unknown token."
                % pretrained_file_path)
        if loaded_unknown_vec is not None and len(loaded_unknown_vec) != vec_len:
            raise ValueError(
                "Unknown-token vector in %s has dimension %d but other "
                "tokens have %d."
                % (pretrained_file_path, len(loaded_unknown_vec), vec_len))
        self._vec_len = vec_len
        import numpy as np
        mat = np.zeros((1 + len(all_rows), vec_len), dtype="float32")
        if all_rows:
            mat[1:] = np.asarray(all_rows, dtype="float32")
        if loaded_unknown_vec is None:
            mat[UNKNOWN_IDX] = init_unknown_vec(shape=vec_len).asnumpy()
        else:
            mat[UNKNOWN_IDX] = np.asarray(loaded_unknown_vec,
                                          dtype="float32")
        self._idx_to_vec = nd.array(mat)

    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = (list(vocabulary.reserved_tokens)
                                 if vocabulary.reserved_tokens is not None
                                 else None)

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        """Concatenate vectors from one or more embeddings per vocabulary
        token (ref embedding.py:313-341)."""
        import numpy as np
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        out = np.zeros((vocab_len, new_vec_len), dtype="float32")
        col = 0
        for embed in token_embeddings:
            end = col + embed.vec_len
            out[0, col:end] = embed.idx_to_vec[0].asnumpy()
            if vocab_len > 1:
                out[1:, col:end] = embed.get_vecs_by_tokens(
                    vocab_idx_to_token[1:]).asnumpy()
            col = end
        self._vec_len = new_vec_len
        self._idx_to_vec = nd.array(out)

    def _build_embedding_for_vocabulary(self, vocabulary):
        if vocabulary is not None:
            if not isinstance(vocabulary, _vocab.Vocabulary):
                raise TypeError("`vocabulary` must be an instance of "
                                "Vocabulary.")
            self._set_idx_to_vec_by_embeddings(
                [self], len(vocabulary), vocabulary.idx_to_token)
            self._index_tokens_from_vocabulary(vocabulary)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the unknown vector,
        optionally retrying lower-cased (ref embedding.py:365)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if not lower_case_backup:
            idxs = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in toks]
        else:
            idxs = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), UNKNOWN_IDX))
                for t in toks]
        import numpy as np
        # device-side row gather — never copies the whole matrix to host
        vecs = nd.take(self._idx_to_vec,
                       nd.array(np.asarray(idxs, np.float32)), axis=0)
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of known tokens (ref embedding.py:404)."""
        if self._idx_to_vec is None:
            raise ValueError("The property `idx_to_vec` has not been "
                             "properly set.")
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        import numpy as np
        newv = new_vectors.asnumpy()
        if newv.ndim == 1:
            newv = newv[None, :]
        if len(toks) != newv.shape[0]:
            raise ValueError("The length of `tokens` and the number of "
                             "rows of `new_vectors` must match.")
        idxs = []
        for t in toks:
            if t in self._token_to_idx:
                idxs.append(self._token_to_idx[t])
            else:
                raise ValueError(
                    "Token %s is unknown. To update the embedding vector "
                    "for an unknown token, please specify it explicitly "
                    "as the `unknown_token` %s."
                    % (t, self.unknown_token))
        # device-side row scatter — never copies the whole matrix to host
        import jax.numpy as jnp
        from ..ndarray import NDArray
        mat = self._idx_to_vec._data.at[jnp.asarray(idxs)].set(
            jnp.asarray(newv, self._idx_to_vec._data.dtype))
        self._idx_to_vec = NDArray(mat)

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_names:
            raise KeyError(
                "Cannot find pretrained file %s for token embedding %s. "
                "Valid pretrained files: %s"
                % (pretrained_file_name, cls.__name__.lower(),
                   ", ".join(cls.pretrained_file_names)))


# reference name: module-private base class alias
_TokenEmbedding = TokenEmbedding


@register
class GloVe(TokenEmbedding):
    """GloVe embeddings (ref embedding.py:468). Requires the unpacked
    .txt file locally under ``embedding_root``/glove/."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root,
                                         pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(TokenEmbedding):
    """fastText embeddings (ref embedding.py:558). Requires the .vec
    file locally under ``embedding_root``/fasttext/."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.fr.vec",
        "wiki.de.vec", "wiki.es.vec", "wiki.ja.vec", "wiki.ru.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root,
                                         pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


class CustomEmbedding(TokenEmbedding):
    """Embedding from a user file `token<delim>v1<delim>v2...`
    (ref embedding.py:658)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(TokenEmbedding):
    """Concatenation of multiple embeddings over one vocabulary
    (ref embedding.py:719)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, _vocab.Vocabulary):
            raise TypeError("`vocabulary` must be an instance of "
                            "Vocabulary.")
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for embed in token_embeddings:
            if not isinstance(embed, TokenEmbedding):
                raise TypeError("`token_embeddings` must contain "
                                "TokenEmbedding instances.")
        super().__init__()
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(vocabulary), vocabulary.idx_to_token)
        self._index_tokens_from_vocabulary(vocabulary)
