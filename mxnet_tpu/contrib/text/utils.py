"""Text corpus helpers (behavioral parity:
python/mxnet/contrib/text/utils.py:28, count_tokens_from_str)."""
from __future__ import annotations

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Tokenise ``source_str`` on the union of the two delimiter regexes and
    tally token frequencies.  Updates and returns ``counter_to_update`` when
    given, else returns a fresh ``Counter``."""
    if counter_to_update is None:
        counter_to_update = collections.Counter()
    splitter = re.compile(f"(?:{token_delim})|(?:{seq_delim})")
    for piece in splitter.split(source_str):
        if not piece:
            continue
        counter_to_update[piece.lower() if to_lower else piece] += 1
    return counter_to_update
