"""Legacy contrib autograd API.

Reference parity: python/mxnet/contrib/autograd.py:32-226 — the pre-Gluon
autograd surface (train_section/test_section scopes, compute_gradient,
grad_and_loss/grad decorators). Thin adapters over mxnet_tpu.autograd's
tape (which replaces the reference's global C-side recording flags).
"""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..ndarray import NDArray, zeros_like

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Enter/leave recording+training mode globally (ref
    contrib/autograd.py:32 flips both MXAutogradSetIsTraining and
    SetIsRecording). Returns the previous state."""
    prev = _ag.is_training() and _ag.is_recording()
    _ag._state.recording = bool(is_train)
    _ag._state.training = bool(is_train)
    return prev


def train_section():
    """``with train_section():`` — record with is_train=True
    (ref contrib/autograd.py:74)."""
    return _ag.record(train_mode=True)


def test_section():
    """``with test_section():`` — predict-mode recording
    (ref contrib/autograd.py:88)."""
    return _ag.record(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    return _ag.backward(outputs, out_grads, retain_graph)


def compute_gradient(outputs):
    """Alias of backward (ref contrib/autograd.py:158)."""
    return backward(outputs)


def _select_args(args, argnum):
    """Pick the differentiated subset of positional args (all by default)
    and type-check them."""
    if argnum is None:
        chosen = list(args)
    else:
        indices = argnum if isinstance(argnum, list) else [argnum]
        chosen = [args[i] for i in indices]
    for x in chosen:
        if not isinstance(x, NDArray):
            raise TypeError("type of autograd input should NDArray.")
    return chosen


def grad_and_loss(func, argnum=None):
    """Decorate ``func`` to return (arg_gradients, loss)
    (ref contrib/autograd.py:163)."""

    @functools.wraps(func)
    def wrapped(*args):
        variables = _select_args(args, argnum)
        grads = [zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        heads = [outputs] if isinstance(outputs, NDArray) else outputs
        compute_gradient(heads)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """Decorate ``func`` to return only the argument gradients
    (ref contrib/autograd.py:195)."""
    with_loss = grad_and_loss(func, argnum)

    @functools.wraps(with_loss)
    def wrapped(*args):
        gradients, _ = with_loss(*args)
        return gradients

    return wrapped
