"""Post-training INT8 quantization.

Reference parity: python/mxnet/contrib/quantization.py:412
(quantize_model) over src/operator/quantization/. The transform walks
the Symbol DAG and rewraps each Convolution/FullyConnected node as

    quantize(data) -> quantized_op(int8xint8 -> int32)
        -> requantize(calibrated range) -> dequantize

so the heavy math runs int8 on the MXU while every surrounding op sees
fp32 (the reference chains quantized ops more aggressively to skip
intermediate dequantize/quantize pairs — a fusion XLA largely recovers
by eliding the back-to-back rescales).

Calibration modes (reference calib_mode):
- 'none'   — requantize uses the per-batch actual int32 range,
- 'naive'  — run calib batches through the fp32 net, record per-layer
             output min/max, bake them in as requantize calib ranges,
- 'entropy'— like 'naive' but pick per-layer thresholds minimizing the
             KL divergence between the fp32 histogram and its quantized
             projection (reference _LayerOutputMinMaxCollector /
             _get_optimal_thresholds).
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_symbol"]

_QUANTIZABLE = {"Convolution": "_contrib_quantized_conv",
                "FullyConnected": "_contrib_quantized_fully_connected"}


def quantize_symbol(sym, excluded_sym_names=(), offline_params=(),
                    calib_ranges=None, param_shapes=None,
                    quantized_dtype="int8"):
    """Rewrite ``sym`` with 8-bit conv/FC (see module docstring).
    ``calib_ranges``: {node_name: (min, max)} output ranges from
    calibration; nodes without a range requantize on the fly.
    ``param_shapes``: {name: shape} stamped as ``__shape__`` on the
    parameter variables — the quantize chain between a weight var and
    its consumer blocks backward shape inference, so the shapes the
    caller already knows (from arg_params) ride along explicitly.
    ``quantized_dtype``: 'int8' (zero-centered), 'uint8' (affine
    activations; weights stay int8 like the reference's deployed
    combination), or 'auto' (uint8 where the activation is provably
    non-negative — fed by a ReLU — else int8)."""
    from ..symbol import Symbol
    from ..symbol.symbol import _Node
    from ..ops import registry as _reg

    excluded = set(excluded_sym_names)
    offline_params = set(offline_params)
    calib_ranges = calib_ranges or {}
    q_op = {k: _reg.get_op(v) for k, v in _QUANTIZABLE.items()}
    op_quantize = _reg.get_op("_contrib_quantize")
    op_requant = _reg.get_op("_contrib_requantize")
    op_dequant = _reg.get_op("_contrib_dequantize")
    op_min = _reg.get_op("min")
    op_max = _reg.get_op("max")

    mapping = {}  # id(old_node) -> new node

    def _fp32_entry(entry):
        node, oi = entry
        return (mapping[id(node)], oi)

    # ops that preserve non-negativity (sign-transparent), for 'auto'
    _SIGN_TRANSPARENT = {"Flatten", "Reshape", "reshape", "transpose",
                         "squeeze", "expand_dims", "Pooling", "UpSampling",
                         "slice", "slice_axis", "Dropout"}

    def _act_dtype(entry):
        """Activation dtype under the requested mode ('auto': uint8 only
        when the value is provably non-negative — produced by a ReLU,
        possibly through shape/pooling ops that cannot change sign)."""
        if quantized_dtype == "uint8":
            return "uint8"
        if quantized_dtype == "auto":
            node, oi = entry
            for _ in range(16):             # bounded walk to the producer
                if node.is_var:
                    break
                name = node.op.name
                if (name == "Activation"
                        and node.attrs.get("act_type") == "relu") \
                        or name in ("relu", "sigmoid", "softmax", "abs"):
                    return "uint8"
                if name in _SIGN_TRANSPARENT and node.inputs:
                    node, oi = node.inputs[0]
                    continue
                break
        return "int8"

    def _quantize_chain(entry, name, out_type="int8"):
        """fp32 entry -> (q_entry, min_entry, max_entry) via online
        min/max + quantize (reference inserts _contrib_quantize the same
        way; ranges for activations are computed on the fly)."""
        src = _fp32_entry(entry)
        mn = _Node(op_min, name + "_min", {}, [src])
        mx_ = _Node(op_max, name + "_max", {}, [src])
        q = _Node(op_quantize, name + "_quantize", {"out_type": out_type},
                  [src, (mn, 0), (mx_, 0)])
        return (q, 0), (q, 1), (q, 2)

    param_shapes = param_shapes or {}
    for node in sym._topo():
        if node.is_var:
            if node.name in param_shapes:
                sa = dict(node.str_attrs)
                sa["__shape__"] = str(tuple(param_shapes[node.name]))
                mapping[id(node)] = _Node(None, node.name, {}, [], sa)
            else:
                mapping[id(node)] = node
            continue
        new_inputs = [(mapping[id(inp)], oi) for inp, oi in node.inputs]
        if node.op.name not in _QUANTIZABLE or node.name in excluded \
                or node.attrs.get("num_group", 1) != 1:
            mapping[id(node)] = _Node(node.op, node.name, dict(node.attrs),
                                      new_inputs, dict(node.str_attrs))
            continue

        # quantized replacement: quantize data online; weights come
        # pre-quantized as int8 vars when listed in offline_params
        # (reference quantize_model bakes them into qarg_params), else
        # they quantize online like activations
        data_q, data_min, data_max = _quantize_chain(
            node.inputs[0], node.name + "_data",
            out_type=_act_dtype(node.inputs[0]))
        w_node = node.inputs[1][0]
        if w_node.is_var and w_node.name in offline_params:
            wshape = param_shapes.get(w_node.name)
            qname = w_node.name + "_quantize"
            sa = {"__dtype__": "int8"}
            if wshape is not None:
                sa["__shape__"] = str(tuple(wshape))
            qw_var = _Node(None, qname, {}, [], sa)
            lo_var = _Node(None, qname + "_min", {}, [],
                           {"__shape__": "(1,)"})
            hi_var = _Node(None, qname + "_max", {}, [],
                           {"__shape__": "(1,)"})
            w_q, w_min, w_max = (qw_var, 0), (lo_var, 0), (hi_var, 0)
        else:
            w_q, w_min, w_max = _quantize_chain(node.inputs[1],
                                                node.name + "_weight")
        attrs = {k: v for k, v in node.attrs.items()
                 if k not in ("no_bias", "cudnn_tune", "cudnn_off",
                              "workspace")}
        attrs["no_bias"] = True
        qnode = _Node(q_op[node.op.name], node.name + "_quantized", attrs,
                      [data_q, w_q, data_min, data_max, w_min, w_max])
        rq_attrs = {}
        if node.name in calib_ranges:
            lo, hi = calib_ranges[node.name]
            rq_attrs = {"min_calib_range": float(lo),
                        "max_calib_range": float(hi)}
        rq = _Node(op_requant, node.name + "_requantize", rq_attrs,
                   [(qnode, 0), (qnode, 1), (qnode, 2)])
        dq = _Node(op_dequant, node.name + "_dequantize", {},
                   [(rq, 0), (rq, 1), (rq, 2)])
        out = dq
        # re-apply the bias in fp32 (the reference folds it via
        # quantized bias inputs; adding it post-dequantize is exact)
        if not node.attrs.get("no_bias", False) and len(node.inputs) > 2:
            add = _reg.get_op("broadcast_add")
            bias_entry = _fp32_entry(node.inputs[2])
            if node.op.name == "Convolution":
                rs = _reg.get_op("reshape")
                ndim = len(tuple(node.attrs["kernel"])) + 2
                bias_r = _Node(rs, node.name + "_bias_r",
                               {"shape": (1, -1) + (1,) * (ndim - 2)},
                               [bias_entry])
                bias_entry = (bias_r, 0)
            out = _Node(add, node.name + "_bias_add", {},
                        [(dq, 0), bias_entry])
        mapping[id(node)] = out

    return Symbol([(mapping[id(n)], oi) for n, oi in sym._entries])


def _collect_layer_outputs(sym, arg_params, aux_params, calib_data,
                           data_names, label_names, max_batches, ctx,
                           collect):
    """Run fp32 forward over calib batches, feeding every targeted
    node's output into ``collect(name, np_array)``."""
    from .. import io as _io
    from ..module import Module
    wanted = {n.name for n in sym._topo()
              if not n.is_var and n.op.name in _QUANTIZABLE}
    mod = Module(sym, data_names=data_names,
                 label_names=list(label_names or []), context=ctx)
    provide_label = calib_data.provide_label if label_names else None
    mod.bind(data_shapes=calib_data.provide_data,
             label_shapes=provide_label, for_training=False)
    mod.set_params(arg_params, aux_params)

    # the quantized graph requantizes the PRE-bias accumulator (bias is
    # re-added after dequantize), so calibration must see bias-free
    # outputs — subtract each node's bias from the tapped samples
    biases = {}
    for n in sym._topo():
        if n.is_var or n.name not in wanted:
            continue
        if not n.attrs.get("no_bias", False) and len(n.inputs) > 2:
            bname = n.inputs[2][0].name
            if bname in arg_params:
                b = arg_params[bname].asnumpy()
                if n.op.name == "Convolution":
                    nd_ = len(tuple(n.attrs["kernel"])) + 2
                    b = b.reshape((1, -1) + (1,) * (nd_ - 2))
                biases[n.name] = b

    def callback(name, arr):
        base = name[:-len("_output")] if name.endswith("_output") else name
        if base in wanted:
            sample = arr.asnumpy()
            if base in biases:
                sample = sample - biases[base]
            collect(base, sample)

    mod.install_monitor(type("M", (), {"stat_helper": staticmethod(callback),
                                       "monitor_all": False})())
    calib_data.reset()
    for i, batch in enumerate(calib_data):
        if i >= max_batches:
            break
        mod.forward(batch, is_train=False)
        for o in mod.get_outputs():
            o.wait_to_read()
    return wanted


def _entropy_threshold(samples, num_bins=2048, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| (reference
    _get_optimal_threshold, contrib/quantization.py)."""
    arr = _np.abs(_np.concatenate([s.ravel() for s in samples]))
    amax = float(arr.max()) if arr.size else 0.0
    if amax == 0.0:
        return 1e-8
    hist, edges = _np.histogram(arr, bins=num_bins, range=(0, amax))
    total = hist.sum()
    best_kl, best_t = _np.inf, amax
    # candidate thresholds sweep the top half of the histogram
    for i in range(num_quantized_bins // 2, num_bins + 1,
                   max(num_bins // 64, 1)):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(_np.float64).copy()
        outliers = hist[i:].sum()
        if p.size == 0 or p.sum() + outliers == 0:
            continue
        p[-1] += outliers
        # project p onto num_quantized_bins then expand back
        factor = p.size / num_quantized_bins
        q = _np.zeros_like(p)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = int((j + 1) * factor) or lo + 1
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        pm = p / p.sum()
        qm = q / q.sum() if q.sum() else q
        mask = pm > 0
        kl = float(_np.sum(_np.where(
            mask & (qm > 0), pm * _np.log(_np.maximum(pm, 1e-30)
                                          / _np.maximum(qm, 1e-30)), 0)))
        kl += float(_np.sum(pm[mask & (qm <= 0)]))  # infinite-KL penalty
        if kl < best_kl:
            best_kl, best_t = kl, t
    # guard against over-clipping on small calibration sets: never cut
    # below the 99.5th percentile of observed magnitudes
    return max(best_t, float(_np.percentile(arr, 99.5)))


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=(), calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging):
    """Quantize a trained fp32 model (reference
    contrib/quantization.py:412 quantize_model). Returns
    (qsym, qarg_params, aux_params)."""
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError("quantized_dtype must be int8/uint8/auto "
                         "(reference quantize-inl.h out_type)")
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError("calib_mode must be none/naive/entropy")

    calib_ranges = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_mode=%s requires calib_data"
                             % calib_mode)
        batch_size = calib_data.provide_data[0].shape[0]
        max_batches = ((num_calib_examples or batch_size) + batch_size - 1) \
            // batch_size
        stats = {}

        def collect(name, arr):
            stats.setdefault(name, []).append(arr)

        _collect_layer_outputs(sym, arg_params, aux_params, calib_data,
                               list(data_names), list(label_names or []),
                               max_batches, ctx, collect)
        for name, samples in stats.items():
            if calib_mode == "naive":
                t = max(abs(float(min(s.min() for s in samples))),
                        abs(float(max(s.max() for s in samples))))
            else:
                t = _entropy_threshold(samples)
            calib_ranges[name] = (-t, t)
            logger.info("calibrated %s: |range|=%.4f (%s)", name, t,
                        calib_mode)

    # weights of quantizable nodes are quantized offline into qarg_params
    # (reference quantize_params) so inference never re-quantizes them.
    # A weight shared with any non-quantized consumer must keep its fp32
    # var (that consumer still reads it), so it stays on the online path.
    excluded_set = set(excluded_sym_names)

    def _is_quantized_node(n):
        return (not n.is_var and n.op.name in _QUANTIZABLE
                and n.name not in excluded_set
                and n.attrs.get("num_group", 1) == 1)

    candidates, shared_fp32 = set(), set()
    for node in sym._topo():
        if node.is_var:
            continue
        for pos, (inp, _) in enumerate(node.inputs):
            if not (inp.is_var and inp.name in arg_params):
                continue
            if _is_quantized_node(node) and pos == 1:
                candidates.add(inp.name)
            else:
                shared_fp32.add(inp.name)
    offline = sorted(candidates - shared_fp32)

    qsym = quantize_symbol(
        sym, excluded_sym_names=excluded_sym_names,
        offline_params=offline, calib_ranges=calib_ranges,
        param_shapes={k: tuple(v.shape) for k, v in arg_params.items()},
        quantized_dtype=quantized_dtype)

    from .. import ndarray as _nd
    qarg_params = dict(arg_params)
    for name in offline:
        w = arg_params[name]
        wn = w.asnumpy()
        # ranges must live with the weight (a cpu-context checkpoint on a
        # TPU-default process would otherwise mix contexts)
        lo = _nd.array(_np.float32(float(wn.min())), ctx=w.context)
        hi = _nd.array(_np.float32(float(wn.max())), ctx=w.context)
        # weights are ALWAYS zero-centered int8 (the reference's deployed
        # combination: uint8 activations x int8 weights)
        qw, qlo, qhi = _nd.quantize(w, lo, hi, out_type="int8")
        qarg_params[name + "_quantize"] = qw
        qarg_params[name + "_quantize_min"] = qlo
        qarg_params[name + "_quantize_max"] = qhi
        # the fp32 original is no longer an argument of qsym
        del qarg_params[name]
    return qsym, qarg_params, dict(aux_params or {})
