"""mx.contrib.ndarray — the imperative contrib op namespace.

Reference parity: python/mxnet/contrib/ndarray.py (generated module
re-exporting every _contrib_* op). Same objects as ``mx.nd.contrib``.
"""
from ..ndarray import contrib as _c

__all__ = []
for _n in dir(_c):
    if not _n.startswith("_"):
        globals()[_n] = getattr(_c, _n)
        __all__.append(_n)
