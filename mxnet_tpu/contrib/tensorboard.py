"""TensorBoard metric logging callback.

Reference parity: python/mxnet/contrib/tensorboard.py (LogMetricsCallback
over mxboard's SummaryWriter). Here the writer resolves in order:
mxboard → torch.utils.tensorboard → a built-in JSONL scalar writer (one
``{"tag", "value", "step"}`` object per line under ``logging_dir``), so
metric logging works without optional dependencies.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback", "JsonlSummaryWriter"]


class JsonlSummaryWriter:
    """Fallback scalar writer: newline-delimited JSON events."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, "scalars.jsonl")
        self._f = open(self._path, "a")

    def add_scalar(self, tag, value, global_step=None):
        self._f.write(json.dumps({"tag": tag, "value": float(value),
                                  "step": global_step,
                                  "time": time.time()}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


def _make_writer(logging_dir):
    try:
        from mxboard import SummaryWriter      # noqa: F401
        return SummaryWriter(logdir=logging_dir)
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=logging_dir)
    except Exception:
        pass
    return JsonlSummaryWriter(logging_dir)


class LogMetricsCallback(object):
    """Batch/epoch-end callback writing each metric as a scalar series
    (ref contrib/tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _make_writer(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
