"""Global PRNG state (reference: python/mxnet/random.py, src/resource.cc kRandom).

TPU-native: a single JAX PRNG key chain. Eager random ops split off this
chain; jitted executors instead thread a per-step key through OpContext so
compiled computations stay pure.
"""
from __future__ import annotations

import jax

__all__ = ["seed", "next_key"]

_STATE = {"key": None, "seed": 0}


def seed(seed_state):
    """Seed the global RNG (parity with mx.random.seed)."""
    _STATE["seed"] = int(seed_state)
    _STATE["key"] = jax.random.PRNGKey(int(seed_state))


def next_key():
    if _STATE["key"] is None:
        _STATE["key"] = jax.random.PRNGKey(_STATE["seed"])
    _STATE["key"], sub = jax.random.split(_STATE["key"])
    return sub
