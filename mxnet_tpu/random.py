"""Global PRNG state (reference: python/mxnet/random.py, src/resource.cc kRandom).

TPU-native: a single JAX PRNG key chain. Eager random ops split off this
chain; jitted executors instead thread a per-step key through OpContext so
compiled computations stay pure.
"""
from __future__ import annotations

import jax
import numpy as _np

__all__ = ["seed", "next_key", "next_seed", "get_state", "set_state",
           "uniform", "normal", "randint", "exponential", "gamma",
           "poisson", "multinomial", "shuffle", "randn"]

_STATE = {"key": None, "seed": 0, "host_rng": None}


def seed(seed_state):
    """Seed the global RNG (parity with mx.random.seed)."""
    _STATE["seed"] = int(seed_state)
    _STATE["key"] = jax.random.PRNGKey(int(seed_state))
    _STATE["host_rng"] = _np.random.RandomState(int(seed_state) & 0xFFFFFFFF)


def next_key():
    if _STATE["key"] is None:
        _STATE["key"] = jax.random.PRNGKey(_STATE["seed"])
    _STATE["key"], sub = jax.random.split(_STATE["key"])
    return sub


def next_seed():
    """A uint32 seed drawn from the framework's seeded host stream.

    Used by jitted paths (hybridized blocks, executors) that pass a scalar
    seed into the compiled computation — keeps their dropout reproducible
    via :func:`seed` without touching numpy's global RNG. If :func:`seed`
    was never called the stream is entropy-seeded (distinct per process),
    matching the reference's unseeded behavior.
    """
    if _STATE["host_rng"] is None:
        _STATE["host_rng"] = _np.random.RandomState()  # OS entropy
    return _np.uint32(_STATE["host_rng"].randint(0, 2 ** 31 - 1))


def get_state():
    """Snapshot the whole RNG chain (mx.checkpoint): the seed, the JAX
    key chain position, and the host stream's Mersenne state. The key
    comes back as a plain int list (JSON-able); the host state is the
    numpy ``get_state()`` tuple."""
    key = _STATE["key"]
    host = _STATE["host_rng"]
    return {"seed": int(_STATE["seed"]),
            "key": None if key is None
            else _np.asarray(key, dtype=_np.uint32).tolist(),
            "host": None if host is None else host.get_state()}


def set_state(state):
    """Restore a :func:`get_state` snapshot — a checkpointed-and-resumed
    run continues the exact dropout/shuffle streams of the original."""
    import jax.numpy as jnp
    _STATE["seed"] = int(state.get("seed", 0) or 0)
    key = state.get("key")
    _STATE["key"] = None if key is None \
        else jnp.asarray(_np.asarray(key, dtype=_np.uint32))
    host = state.get("host")
    if host is None:
        _STATE["host_rng"] = None
    else:
        rng = _np.random.RandomState()
        rng.set_state(tuple(host))
        _STATE["host_rng"] = rng


# ----------------------------------------------------------------------
# Sampling surface (reference python/mxnet/random.py re-exports the
# ndarray samplers at module level: mx.random.uniform(-10, 10, shape)).
# ----------------------------------------------------------------------
def _nd_random():
    from .ndarray import random as _r
    return _r


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None,
            out=None, **kw):
    return _nd_random().uniform(low, high, shape, dtype, ctx, out, **kw)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None,
           out=None, **kw):
    return _nd_random().normal(loc, scale, shape, dtype, ctx, out, **kw)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", **kw):
    return _nd_random().normal(loc, scale, tuple(shape) or (1,), dtype)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None, **kw):
    return _nd_random().randint(low, high, shape, dtype, ctx, out, **kw)


def exponential(scale=1.0, shape=(), dtype="float32", ctx=None, out=None,
                **kw):
    return _nd_random().exponential(scale, shape, dtype, ctx, out, **kw)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None,
          out=None, **kw):
    return _nd_random().gamma(alpha, beta, shape, dtype, ctx, out, **kw)


def poisson(lam=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _nd_random().poisson(lam, shape, dtype, ctx, out, **kw)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return _nd_random().multinomial(data, shape, get_prob, dtype, **kw)


def shuffle(data, **kw):
    return _nd_random().shuffle(data, **kw)
