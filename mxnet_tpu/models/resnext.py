"""ResNeXt (reference example/image-classification/symbols/resnext.py).
Grouped 3x3 convs lower to one grouped-conv HLO."""
from .. import symbol as sym


def resnext_unit(data, num_filter, stride, dim_match, name, num_group=32,
                 bottle_neck=True):
    if bottle_neck:
        mid = num_filter // 2
        conv1 = sym.Convolution(data=data, num_filter=mid, kernel=(1, 1),
                                no_bias=True, name=name + "_conv1")
        bn1 = sym.BatchNorm(data=conv1, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu")
        conv2 = sym.Convolution(data=act1, num_filter=mid, kernel=(3, 3),
                                stride=stride, pad=(1, 1),
                                num_group=num_group, no_bias=True,
                                name=name + "_conv2")
        bn2 = sym.BatchNorm(data=conv2, name=name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu")
        conv3 = sym.Convolution(data=act2, num_filter=num_filter,
                                kernel=(1, 1), no_bias=True,
                                name=name + "_conv3")
        body = sym.BatchNorm(data=conv3, name=name + "_bn3")
    else:
        conv1 = sym.Convolution(data=data, num_filter=num_filter,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, name=name + "_conv1")
        bn1 = sym.BatchNorm(data=conv1, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu")
        conv2 = sym.Convolution(data=act1, num_filter=num_filter,
                                kernel=(3, 3), pad=(1, 1), no_bias=True,
                                name=name + "_conv2")
        body = sym.BatchNorm(data=conv2, name=name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        sc = sym.Convolution(data=data, num_filter=num_filter, kernel=(1, 1),
                             stride=stride, no_bias=True, name=name + "_sc")
        shortcut = sym.BatchNorm(data=sc, name=name + "_sc_bn")
    return sym.Activation(data=body + shortcut, act_type="relu",
                          name=name + "_relu")


def get_symbol(num_classes=1000, num_layers=50, num_group=32,
               image_shape=(3, 224, 224), dtype="float32", **kwargs):
    units_by_depth = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                      152: [3, 8, 36, 3]}
    if num_layers not in units_by_depth:
        raise ValueError("no resnext with depth %d" % num_layers)
    units = units_by_depth[num_layers]
    filter_list = [64, 256, 512, 1024, 2048]

    data = sym.Variable("data")
    if dtype in ("float16", "bfloat16"):
        data = sym.Cast(data=data, dtype=dtype)
    body = sym.Convolution(data=data, num_filter=filter_list[0],
                           kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                           no_bias=True, name="conv0")
    body = sym.BatchNorm(data=body, name="bn0")
    body = sym.Activation(data=body, act_type="relu", name="relu0")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                      pool_type="max", name="pool0")
    for i in range(4):
        stride = (1, 1) if i == 0 else (2, 2)
        body = resnext_unit(body, filter_list[i + 1], stride, False,
                            "stage%d_unit1" % (i + 1), num_group)
        for j in range(units[i] - 1):
            body = resnext_unit(body, filter_list[i + 1], (1, 1), True,
                                "stage%d_unit%d" % (i + 1, j + 2), num_group)
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    if dtype in ("float16", "bfloat16"):
        fc = sym.Cast(data=fc, dtype="float32")
    return sym.SoftmaxOutput(data=fc, name="softmax")
