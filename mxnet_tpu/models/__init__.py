"""Symbol-based model zoo.

Reference parity: example/image-classification/symbols/ (mlp, lenet,
alexnet, vgg, resnet, resnext, mobilenet, inception-bn, googlenet,
squeezenet, densenet). Each module exposes ``get_symbol(num_classes, ...)``
returning a Symbol ending in SoftmaxOutput, so any of them drops into
``Module.fit`` / ``bench.py`` unchanged.

These are fresh TPU-first definitions (bf16-friendly: ``dtype`` casts the
trunk while the final classifier/softmax stays fp32), not translations of
the reference scripts.
"""
from . import mlp
from . import lenet
from . import alexnet
from . import vgg
from . import resnet
from . import resnext
from . import mobilenet
from . import inception_bn
from . import googlenet
from . import squeezenet
from . import densenet
from . import transformer

_NETWORKS = {
    "transformer": transformer,
    "mlp": mlp,
    "lenet": lenet,
    "alexnet": alexnet,
    "vgg": vgg,
    "resnet": resnet,
    "resnext": resnext,
    "mobilenet": mobilenet,
    "inception-bn": inception_bn,
    "inception_bn": inception_bn,
    "googlenet": googlenet,
    "squeezenet": squeezenet,
    "densenet": densenet,
}


def get_symbol(network, **kwargs):
    """Factory mirroring example/image-classification/common/fit.py usage:
    ``models.get_symbol('resnet', num_classes=1000, num_layers=50,
    image_shape=(3,224,224))``."""
    if network not in _NETWORKS:
        raise ValueError("unknown network '%s'; available: %s"
                         % (network, sorted(set(_NETWORKS))))
    return _NETWORKS[network].get_symbol(**kwargs)
