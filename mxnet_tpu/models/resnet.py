"""ResNet v1/v2 for ImageNet and CIFAR.

Reference parity: example/image-classification/symbols/resnet.py (v2,
"Identity Mappings in Deep Residual Networks") and resnet-v1.py. Fresh
TPU-first definition: the trunk can run in bf16 (``dtype='bfloat16'``) with
the classifier head kept fp32 — the MXU-friendly configuration — and every
op lowers to a single conv/matmul HLO, so the whole network is one XLA
computation once bound. ``layout='NHWC'`` builds the whole trunk
channel-last (data, weights, pooling, BN axis), the TPU-preferred layout:
no relayout copy anywhere in the step (docs/PERF.md).

Depth table (ImageNet): 18/34 use the basic block, 50/101/152/200 use the
bottleneck block. CIFAR shapes (image < 64px) use the 3-stage layout with
depth = 6n+2 (v2: 9n+2 bottleneck for 164+).
"""
from functools import partial

from .. import symbol as sym

BN_MOM = 0.9
EPS = 2e-5


def _bn(data, name, fix_gamma=False, layout="NCHW"):
    axis = 3 if str(layout).endswith("C") else 1
    return sym.BatchNorm(data=data, name=name, fix_gamma=fix_gamma,
                         eps=EPS, momentum=BN_MOM, axis=axis)


def residual_unit_v2(data, num_filter, stride, dim_match, name,
                     bottle_neck=True, workspace=256, layout="NCHW"):
    """Pre-activation residual unit (BN-ReLU-Conv)."""
    conv = partial(sym.Convolution, layout=layout, workspace=workspace)
    bn = partial(_bn, layout=layout)
    bn1 = bn(data, name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
    if bottle_neck:
        conv1 = conv(data=act1, num_filter=num_filter // 4, kernel=(1, 1),
                     stride=(1, 1), pad=(0, 0), no_bias=True,
                     name=name + "_conv1")
        bn2 = bn(conv1, name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv2 = conv(data=act2, num_filter=num_filter // 4, kernel=(3, 3),
                     stride=stride, pad=(1, 1), no_bias=True,
                     name=name + "_conv2")
        bn3 = bn(conv2, name + "_bn3")
        act3 = sym.Activation(data=bn3, act_type="relu", name=name + "_relu3")
        conv3 = conv(data=act3, num_filter=num_filter, kernel=(1, 1),
                     stride=(1, 1), pad=(0, 0), no_bias=True,
                     name=name + "_conv3")
        body = conv3
    else:
        conv1 = conv(data=act1, num_filter=num_filter, kernel=(3, 3),
                     stride=stride, pad=(1, 1), no_bias=True,
                     name=name + "_conv1")
        bn2 = bn(conv1, name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv2 = conv(data=act2, num_filter=num_filter, kernel=(3, 3),
                     stride=(1, 1), pad=(1, 1), no_bias=True,
                     name=name + "_conv2")
        body = conv2
    if dim_match:
        shortcut = data
    else:
        shortcut = conv(data=act1, num_filter=num_filter, kernel=(1, 1),
                        stride=stride, no_bias=True, name=name + "_sc")
    return body + shortcut


def residual_unit_v1(data, num_filter, stride, dim_match, name,
                     bottle_neck=True, workspace=256, layout="NCHW"):
    """Original residual unit (Conv-BN-ReLU, post-activation)."""
    conv = partial(sym.Convolution, layout=layout, workspace=workspace)
    bn = partial(_bn, layout=layout)
    if bottle_neck:
        conv1 = conv(data=data, num_filter=num_filter // 4, kernel=(1, 1),
                     stride=stride, pad=(0, 0), no_bias=True,
                     name=name + "_conv1")
        bn1 = bn(conv1, name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
        conv2 = conv(data=act1, num_filter=num_filter // 4, kernel=(3, 3),
                     stride=(1, 1), pad=(1, 1), no_bias=True,
                     name=name + "_conv2")
        bn2 = bn(conv2, name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv3 = conv(data=act2, num_filter=num_filter, kernel=(1, 1),
                     stride=(1, 1), pad=(0, 0), no_bias=True,
                     name=name + "_conv3")
        body = bn(conv3, name + "_bn3")
    else:
        conv1 = conv(data=data, num_filter=num_filter, kernel=(3, 3),
                     stride=stride, pad=(1, 1), no_bias=True,
                     name=name + "_conv1")
        bn1 = bn(conv1, name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
        conv2 = conv(data=act1, num_filter=num_filter, kernel=(3, 3),
                     stride=(1, 1), pad=(1, 1), no_bias=True,
                     name=name + "_conv2")
        body = bn(conv2, name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        sc = conv(data=data, num_filter=num_filter, kernel=(1, 1),
                  stride=stride, no_bias=True, name=name + "_sc")
        shortcut = bn(sc, name + "_sc_bn")
    return sym.Activation(data=body + shortcut, act_type="relu",
                          name=name + "_relu")


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, workspace=256, dtype="float32", version=2,
           layout="NCHW"):
    unit_fn = residual_unit_v2 if version == 2 else residual_unit_v1
    conv = partial(sym.Convolution, layout=layout, workspace=workspace)
    bn = partial(_bn, layout=layout)
    (nchannel, height, _width) = image_shape
    data = sym.Variable(name="data")
    if dtype in ("float16", "bfloat16"):
        data = sym.Cast(data=data, dtype=dtype, name="cast_data")
    data = bn(data, "bn_data", fix_gamma=True)
    if height <= 32:  # cifar
        body = conv(data=data, num_filter=filter_list[0], kernel=(3, 3),
                    stride=(1, 1), pad=(1, 1), no_bias=True, name="conv0")
    else:  # imagenet stem
        body = conv(data=data, num_filter=filter_list[0], kernel=(7, 7),
                    stride=(2, 2), pad=(3, 3), no_bias=True, name="conv0")
        body = bn(body, "bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max", name="pool0",
                           layout=layout)

    for i in range(num_stages):
        stride = (1, 1) if i == 0 else (2, 2)
        body = unit_fn(body, filter_list[i + 1], stride, False,
                       name="stage%d_unit%d" % (i + 1, 1),
                       bottle_neck=bottle_neck, workspace=workspace,
                       layout=layout)
        for j in range(units[i] - 1):
            body = unit_fn(body, filter_list[i + 1], (1, 1), True,
                           name="stage%d_unit%d" % (i + 1, j + 2),
                           bottle_neck=bottle_neck, workspace=workspace,
                           layout=layout)
    if version == 2:
        body = bn(body, "bn1")
        body = sym.Activation(data=body, act_type="relu", name="relu1")
    pool1 = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                        pool_type="avg", name="pool1", layout=layout)
    flat = sym.Flatten(data=pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    if dtype in ("float16", "bfloat16"):
        fc1 = sym.Cast(data=fc1, dtype="float32", name="cast_out")
    return sym.SoftmaxOutput(data=fc1, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               conv_workspace=256, dtype="float32", version=2,
               layout="NCHW", **kwargs):
    """``image_shape`` is always given channels-first (C, H, W) for parity
    with the reference CLI; with ``layout='NHWC'`` the bound data variable
    must be fed (N, H, W, C) batches."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    image_shape = tuple(image_shape)
    (_nchannel, height, _width) = image_shape
    if height <= 28:  # cifar/mnist-sized
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            bottle_neck = False
        else:
            raise ValueError("no cifar resnet with depth %d" % num_layers)
        units = per_unit * num_stages
    else:
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            bottle_neck = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            bottle_neck = False
        num_stages = 4
        units_by_depth = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3],
                          50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                          152: [3, 8, 36, 3], 200: [3, 24, 36, 3],
                          269: [3, 30, 48, 8]}
        if num_layers not in units_by_depth:
            raise ValueError("no imagenet resnet with depth %d" % num_layers)
        units = units_by_depth[num_layers]

    return resnet(units=units, num_stages=num_stages, filter_list=filter_list,
                  num_classes=num_classes, image_shape=image_shape,
                  bottle_neck=bottle_neck, workspace=conv_workspace,
                  dtype=dtype, version=version, layout=layout)
