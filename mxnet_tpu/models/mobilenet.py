"""MobileNet v1 (reference example/image-classification/symbols/mobilenet.py).
Depthwise separable convs lower to grouped conv HLOs (feature_group_count)."""
from .. import symbol as sym


def conv_bn(data, num_filter, kernel, stride, pad, name, num_group=1):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, num_group=num_group,
                           no_bias=True, name=name)
    bn = sym.BatchNorm(data=conv, name=name + "_bn")
    return sym.Activation(data=bn, act_type="relu", name=name + "_relu")


def dw_sep(data, in_ch, out_ch, stride, name, alpha=1.0):
    in_ch = int(in_ch * alpha)
    out_ch = int(out_ch * alpha)
    dw = conv_bn(data, in_ch, (3, 3), stride, (1, 1), name + "_dw",
                 num_group=in_ch)
    return conv_bn(dw, out_ch, (1, 1), (1, 1), (0, 0), name + "_pw")


def get_symbol(num_classes=1000, alpha=1.0, dtype="float32", **kwargs):
    data = sym.Variable("data")
    if dtype in ("float16", "bfloat16"):
        data = sym.Cast(data=data, dtype=dtype)
    net = conv_bn(data, int(32 * alpha), (3, 3), (2, 2), (1, 1), "conv1")
    cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
           (256, 256, 1), (256, 512, 2),
           (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
           (512, 512, 1),
           (512, 1024, 2), (1024, 1024, 1)]
    for i, (cin, cout, s) in enumerate(cfg):
        net = dw_sep(net, cin, cout, (s, s), "sep%d" % (i + 2), alpha)
    pool = sym.Pooling(data=net, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc")
    if dtype in ("float16", "bfloat16"):
        fc = sym.Cast(data=fc, dtype="float32")
    return sym.SoftmaxOutput(data=fc, name="softmax")
