"""DenseNet-BC (reference example/image-classification/symbols/densenet.py)."""
from .. import symbol as sym


def _bn_relu_conv(data, num_filter, kernel, pad, name):
    bn = sym.BatchNorm(data=data, name=name + "_bn")
    act = sym.Activation(data=bn, act_type="relu")
    return sym.Convolution(data=act, num_filter=num_filter, kernel=kernel,
                           pad=pad, no_bias=True, name=name + "_conv")


def dense_block(data, num_units, growth_rate, name):
    for i in range(num_units):
        u = "%s_unit%d" % (name, i + 1)
        bottleneck = _bn_relu_conv(data, 4 * growth_rate, (1, 1), (0, 0),
                                   u + "_b")
        new = _bn_relu_conv(bottleneck, growth_rate, (3, 3), (1, 1), u)
        data = sym.Concat(data, new, name=u + "_concat")
    return data


def transition(data, num_filter, name):
    out = _bn_relu_conv(data, num_filter, (1, 1), (0, 0), name)
    return sym.Pooling(data=out, kernel=(2, 2), stride=(2, 2),
                       pool_type="avg", name=name + "_pool")


def get_symbol(num_classes=1000, num_layers=121, growth_rate=32,
               reduction=0.5, **kwargs):
    stages_by_depth = {121: [6, 12, 24, 16], 169: [6, 12, 32, 32],
                       201: [6, 12, 48, 32], 161: [6, 12, 36, 24]}
    if num_layers not in stages_by_depth:
        raise ValueError("no densenet with depth %d" % num_layers)
    stages = stages_by_depth[num_layers]
    if num_layers == 161:
        growth_rate = 48
    init_ch = 2 * growth_rate

    data = sym.Variable("data")
    body = sym.Convolution(data=data, num_filter=init_ch, kernel=(7, 7),
                           stride=(2, 2), pad=(3, 3), no_bias=True,
                           name="conv0")
    body = sym.BatchNorm(data=body, name="bn0")
    body = sym.Activation(data=body, act_type="relu", name="relu0")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool0")
    ch = init_ch
    for i, units in enumerate(stages):
        body = dense_block(body, units, growth_rate, "block%d" % (i + 1))
        ch += units * growth_rate
        if i != len(stages) - 1:
            ch = int(ch * reduction)
            body = transition(body, ch, "trans%d" % (i + 1))
    body = sym.BatchNorm(data=body, name="bn_final")
    body = sym.Activation(data=body, act_type="relu", name="relu_final")
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
