"""SqueezeNet v1.1 (reference example/image-classification/symbols/squeezenet.py)."""
from .. import symbol as sym


def fire(data, squeeze, expand, name):
    sq = sym.Convolution(data=data, num_filter=squeeze, kernel=(1, 1),
                         name="%s_squeeze" % name)
    sq = sym.Activation(data=sq, act_type="relu")
    e1 = sym.Convolution(data=sq, num_filter=expand, kernel=(1, 1),
                         name="%s_e1x1" % name)
    e1 = sym.Activation(data=e1, act_type="relu")
    e3 = sym.Convolution(data=sq, num_filter=expand, kernel=(3, 3),
                         pad=(1, 1), name="%s_e3x3" % name)
    e3 = sym.Activation(data=e3, act_type="relu")
    return sym.Concat(e1, e3, name="%s_concat" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    net = sym.Convolution(data=data, num_filter=64, kernel=(3, 3),
                          stride=(2, 2), name="conv1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = fire(net, 16, 64, "fire2")
    net = fire(net, 16, 64, "fire3")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = fire(net, 32, 128, "fire4")
    net = fire(net, 32, 128, "fire5")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = fire(net, 48, 192, "fire6")
    net = fire(net, 48, 192, "fire7")
    net = fire(net, 64, 256, "fire8")
    net = fire(net, 64, 256, "fire9")
    net = sym.Dropout(data=net, p=0.5)
    net = sym.Convolution(data=net, num_filter=num_classes, kernel=(1, 1),
                          name="conv10")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Pooling(data=net, global_pool=True, kernel=(13, 13),
                      pool_type="avg")
    net = sym.Flatten(data=net)
    return sym.SoftmaxOutput(data=net, name="softmax")
