"""Symbolic 3-layer perceptron for the image-classification examples
(behavioral parity: example/image-classification/symbols/mlp.py)."""
from .. import symbol as sym

_HIDDEN = (128, 64)


def get_symbol(num_classes=10, **kwargs):
    """Flatten → fc(128)/relu → fc(64)/relu → fc(num_classes) → softmax."""
    net = sym.Flatten(data=sym.Variable("data"))
    for i, width in enumerate(_HIDDEN, start=1):
        net = sym.FullyConnected(data=net, name=f"fc{i}", num_hidden=width)
        net = sym.Activation(data=net, name=f"relu{i}", act_type="relu")
    net = sym.FullyConnected(data=net, name=f"fc{len(_HIDDEN) + 1}",
                             num_hidden=num_classes)
    return sym.SoftmaxOutput(data=net, name="softmax")
