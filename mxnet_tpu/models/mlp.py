"""3-layer perceptron (reference example/image-classification/symbols/mlp.py)."""
from .. import symbol as sym


def get_symbol(num_classes=10, **kwargs):
    data = sym.Variable("data")
    net = sym.Flatten(data=data)
    net = sym.FullyConnected(data=net, name="fc1", num_hidden=128)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=64)
    net = sym.Activation(data=net, name="relu2", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc3", num_hidden=num_classes)
    return sym.SoftmaxOutput(data=net, name="softmax")
