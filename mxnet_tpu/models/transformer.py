"""Decoder-only transformer language model (GPT-style, pre-LN).

New TPU-native capability: the reference (MXNet ~1.2) predates
transformers entirely (SURVEY.md §5.7 maps its sequence stack to
RNN/BucketingModule), so this is not a ported symbol — it is the
arithmetic-intensity-dense model family that demonstrates the framework
reaches MXU-bound MFU when the model is not HBM-bandwidth-bound the way
ResNet/BatchNorm is (docs/PERF.md). Attention is the fused
``sym.contrib.CausalSelfAttention`` op (rematerialized backward, fp32
softmax statistics); sequence/context-parallel training of the same
architecture runs through ``parallel.ring_attention``.

Builds a Symbol ending in SoftmaxOutput, so it drops into ``Module.fit``
/ ``parallel.TrainStep`` / ``bench.py`` exactly like the CNN zoo:
``data`` is (batch, seq_len) token ids and ``softmax_label`` is
(batch*seq_len,) next-token targets.
"""
from .. import initializer as _init
from .. import symbol as sym


def get_symbol(num_classes=16384, num_layers=12, d_model=2048, num_heads=16,
               ffn_dim=None, seq_len=1024, dtype="float32", dropout=0.0,
               moe_experts=0, moe_every=2, moe_aux_coeff=0.01,
               tensor_parallel=None, **kwargs):
    """``num_classes`` is the vocabulary size (factory-signature parity
    with the CNN zoo's get_symbol). With ``moe_experts`` > 0 every
    ``moe_every``-th layer's FFN becomes a Switch-MoE
    (sym.contrib.SwitchMoE, num_experts experts, top-1 routing) and the
    load-balancing aux losses join the heads through MakeLoss scaled by
    ``moe_aux_coeff`` — a sparse-expert LM end-to-end in the symbolic
    API.

    ``tensor_parallel`` (docs/SHARDING.md): a mesh-axis name (True means
    "mp") that Megatron-splits every dense layer — attention heads and
    the packed qkv projection partition over the axis (column-parallel),
    the output/ffn_down projections are row-parallel with the psum at
    their replicated outputs, so each transformer block costs exactly
    two all-reduces in forward.  The annotations are plain
    ``__sharding__`` attrs: without a selected mesh the symbol trains
    replicated, unchanged."""
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    lp = float(dropout)
    aux_losses = []

    tp = "mp" if tensor_parallel is True else tensor_parallel
    if tp:
        from .. import sharding as _sharding
        if int(num_heads) < 2:
            raise ValueError("tensor_parallel needs num_heads >= 2")
        _col_w = {_sharding.SHARDING_ATTR: _sharding.spec(tp, None)}
        _col_b = {_sharding.SHARDING_ATTR: _sharding.spec(tp)}
        _row_w = {_sharding.SHARDING_ATTR: _sharding.spec(None, tp)}
        _replicate = lambda s: _sharding.constrain(s)
        _keep_split = lambda s: _sharding.constrain(s, None, None, tp)
    else:
        _col_w = _col_b = _row_w = {}
        _replicate = _keep_split = lambda s: s

    data = sym.Variable("data")                      # (B, S) token ids
    tok = sym.Embedding(data, input_dim=vocab, output_dim=d,
                        name="tok_embed")
    pos = sym.Variable("pos_embed_weight", shape=(1, int(seq_len), d))
    x = sym.broadcast_add(tok, pos, name="embed_add")
    if dtype in ("float16", "bfloat16"):
        x = sym.Cast(data=x, dtype=dtype, name="cast_embed")
    if lp > 0:
        x = sym.Dropout(data=x, p=lp, name="embed_drop")

    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        ln1 = sym.LayerNorm(data=x, name=pre + "ln1")
        # one fused sublayer op: qkv proj + causal MHA + out proj with
        # head-major internal layout (no transposes); weight names keep
        # the unfused FullyConnected convention so checkpoints interop
        attn_kw = {"head_axis": tp} if tp else {}
        proj = sym.contrib.FusedCausalSelfAttention(
            ln1,
            sym.Variable(pre + "qkv_weight", **_col_w),
            sym.Variable(pre + "qkv_bias", init=_init.Zero(), **_col_b),
            sym.Variable(pre + "proj_weight", **_row_w),
            sym.Variable(pre + "proj_bias", init=_init.Zero()),
            num_heads=int(num_heads), name=pre + "attn", **attn_kw)
        if tp:
            proj = _replicate(proj)   # the block's first psum site
        if lp > 0:
            proj = sym.Dropout(data=proj, p=lp, name=pre + "drop1")
        x = x + proj
        ln2 = sym.LayerNorm(data=x, name=pre + "ln2")
        if moe_experts and (i + 1) % max(int(moe_every), 1) == 0:
            # explicit expert-stack variables with per-expert-fan Normal
            # inits (Xavier misreads 3-D stacks: it would treat the
            # trailing dims as conv extents and under-scale ~sqrt(ffn)x)
            w_up = sym.Variable(pre + "moe_expert_up_weight",
                                init=_init.Normal(d ** -0.5))
            w_down = sym.Variable(pre + "moe_expert_down_weight",
                                  init=_init.Normal(ffn ** -0.5))
            moe = sym.contrib.SwitchMoE(
                ln2, expert_up_weight=w_up, expert_down_weight=w_down,
                num_experts=int(moe_experts), num_hidden=ffn,
                k=1, name=pre + "moe")
            h = moe[0]
            aux_losses.append(moe[1])
        else:
            # Megatron FFN: column-parallel up (weight (ffn, d) split on
            # its output rows), gelu on the still-split activation,
            # row-parallel down with the psum at its replicated output
            h = sym.FullyConnected(
                data=ln2,
                weight=sym.Variable(pre + "ffn_up_weight", **_col_w),
                bias=sym.Variable(pre + "ffn_up_bias", init=_init.Zero(),
                                  **_col_b),
                num_hidden=ffn, flatten=False, name=pre + "ffn_up")
            h = _keep_split(h)
            h = sym.LeakyReLU(data=h, act_type="gelu_tanh",
                              name=pre + "gelu")
            h = sym.FullyConnected(
                data=h,
                weight=sym.Variable(pre + "ffn_down_weight", **_row_w),
                bias=sym.Variable(pre + "ffn_down_bias",
                                  init=_init.Zero()),
                num_hidden=d, flatten=False, name=pre + "ffn_down")
            h = _replicate(h)
        if lp > 0:
            h = sym.Dropout(data=h, p=lp, name=pre + "drop2")
        x = x + h

    x = sym.LayerNorm(data=x, name="ln_f")
    logits = sym.FullyConnected(data=x, num_hidden=vocab, flatten=False,
                                name="lm_head")
    if dtype in ("float16", "bfloat16"):
        logits = sym.Cast(data=logits, dtype="float32", name="cast_out")
    flat = sym.Reshape(data=logits, shape=(-1, vocab), name="logits_2d")
    out = sym.SoftmaxOutput(data=flat, name="softmax",
                            normalization="batch")
    if aux_losses:
        total_aux = aux_losses[0] if len(aux_losses) == 1 else \
            sym.add_n(*aux_losses, name="moe_aux_sum")
        aux_head = sym.MakeLoss(
            sym.Cast(total_aux, dtype="float32", name="cast_aux")
            * float(moe_aux_coeff), name="moe_aux_loss")
        return sym.Group([out, aux_head])
    return out


# ----------------------------------------------------------------------
# Generative serving graphs (mx.decode — docs/DECODE.md)
#
# Both symbols below SHARE every weight name with get_symbol(), so the
# training checkpoint binds them with no conversion; they differ only
# in how attention addresses the paged KV cache
# (sym.contrib.PagedDecodeAttention / PagedPrefillAttention).  Cache
# variables carry explicit shapes (they are engine configuration, not
# inferable from data), and all sequence state — positions, lengths,
# block tables — enters as runtime ARRAY inputs so ragged generation
# never retraces the compiled step.
# ----------------------------------------------------------------------
def _tp_attrs(tensor_parallel):
    """Megatron ``__sharding__`` attr dicts for the decode-graph
    factories (mirrors get_symbol's training-side split): returns
    ``(col_w, col_b, row_w, cache)`` — empty dicts when tensor
    parallelism is off, so annotation-free symbols stay byte-identical.
    ``cache`` head-shards the paged KV blocks (num_blocks, block_size,
    H, D) over the axis, which is where the per-device cache-bytes
    saving of TP decode (docs/FLEET.md) comes from."""
    tp = "mp" if tensor_parallel is True else tensor_parallel
    if not tp:
        return {}, {}, {}, {}
    from .. import sharding as _sharding
    return ({_sharding.SHARDING_ATTR: _sharding.spec(tp, None)},
            {_sharding.SHARDING_ATTR: _sharding.spec(tp)},
            {_sharding.SHARDING_ATTR: _sharding.spec(None, tp)},
            {_sharding.SHARDING_ATTR: _sharding.spec(None, None, tp, None)})


def _decode_trunk_vars(pre, col_w={}, col_b={}, row_w={}):
    """The attention sublayer's weight variables, training-graph names."""
    return (sym.Variable(pre + "qkv_weight", **col_w),
            sym.Variable(pre + "qkv_bias", init=_init.Zero(), **col_b),
            sym.Variable(pre + "proj_weight", **row_w),
            sym.Variable(pre + "proj_bias", init=_init.Zero()))


def _ffn_shared_vars(pre, d, ffn, moe_experts, moe_every, layer_idx,
                     col_w={}, col_b={}, row_w={}):
    """Explicit post-attention sublayer weight Variables (training-graph
    names) so the mixed-step symbol's two streams — decode slots and the
    prefill chunk — bind ONE copy of every parameter."""
    use_moe = moe_experts and (layer_idx + 1) % max(int(moe_every), 1) == 0
    shared = {
        "ln2_gamma": sym.Variable(pre + "ln2_gamma"),
        "ln2_beta": sym.Variable(pre + "ln2_beta", init=_init.Zero()),
    }
    if use_moe:
        shared.update({
            "router_weight": sym.Variable(pre + "moe_router_weight"),
            "expert_up_weight": sym.Variable(
                pre + "moe_expert_up_weight", init=_init.Normal(d ** -0.5)),
            "expert_up_bias": sym.Variable(pre + "moe_expert_up_bias",
                                           init=_init.Zero()),
            "expert_down_weight": sym.Variable(
                pre + "moe_expert_down_weight",
                init=_init.Normal(ffn ** -0.5)),
            "expert_down_bias": sym.Variable(pre + "moe_expert_down_bias",
                                             init=_init.Zero()),
        })
    else:
        shared.update({
            "up_weight": sym.Variable(pre + "ffn_up_weight", **col_w),
            "up_bias": sym.Variable(pre + "ffn_up_bias",
                                    init=_init.Zero(), **col_b),
            "down_weight": sym.Variable(pre + "ffn_down_weight", **row_w),
            "down_bias": sym.Variable(pre + "ffn_down_bias",
                                      init=_init.Zero()),
        })
    return shared


def _decode_ffn(x, pre, d, ffn, moe_experts, moe_every, layer_idx,
                shared=None, tag=""):
    """Post-attention FFN sublayer shared by the decode/prefill graphs
    (inference form: MoE aux losses are dropped, dropout is off).

    ``shared`` (a `_ffn_shared_vars` dict) passes every weight as an
    explicit Variable — the mixed-step symbol instantiates this sublayer
    twice against ONE parameter set; ``tag`` keeps the second instance's
    op names distinct (variable names are unchanged either way)."""
    use_moe = moe_experts and (layer_idx + 1) % max(int(moe_every), 1) == 0
    ln_kw = ({"gamma": shared["ln2_gamma"], "beta": shared["ln2_beta"]}
             if shared else {})
    ln2 = sym.LayerNorm(data=x, name=pre + tag + "ln2", **ln_kw)
    if use_moe:
        if shared:
            w_up, w_down = (shared["expert_up_weight"],
                            shared["expert_down_weight"])
            moe_kw = {"router_weight": shared["router_weight"],
                      "expert_up_bias": shared["expert_up_bias"],
                      "expert_down_bias": shared["expert_down_bias"]}
        else:
            w_up = sym.Variable(pre + "moe_expert_up_weight",
                                init=_init.Normal(d ** -0.5))
            w_down = sym.Variable(pre + "moe_expert_down_weight",
                                  init=_init.Normal(ffn ** -0.5))
            moe_kw = {}
        moe = sym.contrib.SwitchMoE(
            ln2, expert_up_weight=w_up, expert_down_weight=w_down,
            num_experts=int(moe_experts), num_hidden=ffn,
            k=1, name=pre + tag + "moe", **moe_kw)
        return moe[0]
    up_kw = ({"weight": shared["up_weight"], "bias": shared["up_bias"]}
             if shared else {})
    down_kw = ({"weight": shared["down_weight"],
                "bias": shared["down_bias"]} if shared else {})
    h = sym.FullyConnected(data=ln2, num_hidden=ffn, flatten=False,
                           name=pre + tag + "ffn_up", **up_kw)
    h = sym.LeakyReLU(data=h, act_type="gelu_tanh", name=pre + tag + "gelu")
    return sym.FullyConnected(data=h, num_hidden=d, flatten=False,
                              name=pre + tag + "ffn_down", **down_kw)


def get_decode_step_symbol(num_classes=16384, num_layers=12, d_model=2048,
                           num_heads=16, ffn_dim=None, seq_len=1024,
                           dtype="float32", block_size=16, num_blocks=64,
                           moe_experts=0, moe_every=2, **kwargs):
    """One cached autoregressive decode step over C fixed batch slots.

    Inputs (bound shapes set capacity C and table width M):
      ``data`` (C, 1) current token ids; ``positions`` (C, 1) 0-based
      position of that token (< 0 = inactive slot); ``block_table``
      (C, M) per-slot cache block ids; plus per-layer
      ``layer%d_k_cache`` / ``layer%d_v_cache`` paged caches of shape
      (num_blocks, block_size, H, D) that the engine threads from step
      to step.
    Outputs: ``[logits (C, vocab), greedy next token (C,),
    new_k_cache_0, new_v_cache_0, ...]`` — the greedy token ships as
    its own output so a default decode step reads back C ints, not a
    (C, vocab) logits matrix; samplers read output 0 instead.
    """
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    H = int(num_heads)
    D = d // H

    data = sym.Variable("data")                      # (C, 1) token ids
    positions = sym.Variable("positions")            # (C, 1)
    table = sym.Variable("block_table")              # (C, M)
    tok = sym.Embedding(data, input_dim=vocab, output_dim=d,
                        name="tok_embed")
    pos_w = sym.Variable("pos_embed_weight", shape=(1, int(seq_len), d))
    pe = sym.take(sym.Reshape(pos_w, shape=(int(seq_len), d)), positions,
                  name="pos_take")                   # (C, 1, d), clipped
    x = tok + pe
    if dtype in ("float16", "bfloat16"):
        x = sym.Cast(data=x, dtype=dtype, name="cast_embed")

    new_kv = []
    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        ln1 = sym.LayerNorm(data=x, name=pre + "ln1")
        kc = sym.Variable(pre + "k_cache",
                          shape=(int(num_blocks), int(block_size), H, D))
        vc = sym.Variable(pre + "v_cache",
                          shape=(int(num_blocks), int(block_size), H, D))
        att = sym.contrib.PagedDecodeAttention(
            ln1, *_decode_trunk_vars(pre), kc, vc, table, positions,
            num_heads=H, name=pre + "attn")
        x = x + att[0]
        new_kv += [att[1], att[2]]
        x = x + _decode_ffn(x, pre, d, ffn, moe_experts, moe_every, i)

    x = sym.LayerNorm(data=x, name="ln_f")
    logits = sym.FullyConnected(data=x, num_hidden=vocab, flatten=False,
                                name="lm_head")      # (C, 1, vocab)
    if dtype in ("float16", "bfloat16"):
        logits = sym.Cast(data=logits, dtype="float32", name="cast_out")
    flat = sym.Reshape(data=logits, shape=(-1, vocab), name="logits_2d")
    nxt = sym.argmax(flat, axis=1, name="greedy_token")
    return sym.Group([flat, nxt] + new_kv)


def get_prefill_symbol(num_classes=16384, num_layers=12, d_model=2048,
                       num_heads=16, ffn_dim=None, seq_len=1024,
                       prefill_len=None, dtype="float32", block_size=16,
                       num_blocks=64, moe_experts=0, moe_every=2, **kwargs):
    """Prompt-phase forward that populates the paged KV cache.

    ``prefill_len`` is this bucket's padded prompt length S_b (the
    engine keeps a power-of-two ladder of these symbols, one compile
    each — the decode analog of serving's batch-size buckets).  Inputs:
    ``data`` (B, S_b) padded prompt ids, ``prompt_len`` (B,) real
    lengths, ``block_table`` (B, M), plus the same per-layer cache
    variables as the decode step.  Outputs: ``[last-token logits
    (B, vocab), greedy next token (B,), new caches...]``.
    """
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    H = int(num_heads)
    D = d // H
    S = int(prefill_len) if prefill_len else int(seq_len)
    if S > int(seq_len):
        raise ValueError("prefill_len %d exceeds the position-embedding "
                         "range seq_len=%d" % (S, int(seq_len)))

    data = sym.Variable("data")                      # (B, S) token ids
    lengths = sym.Variable("prompt_len")             # (B,)
    table = sym.Variable("block_table")              # (B, M)
    tok = sym.Embedding(data, input_dim=vocab, output_dim=d,
                        name="tok_embed")
    pos_w = sym.Variable("pos_embed_weight", shape=(1, int(seq_len), d))
    pe = pos_w.slice_axis(axis=1, begin=0, end=S)
    x = sym.broadcast_add(tok, pe, name="embed_add")
    if dtype in ("float16", "bfloat16"):
        x = sym.Cast(data=x, dtype=dtype, name="cast_embed")

    new_kv = []
    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        ln1 = sym.LayerNorm(data=x, name=pre + "ln1")
        kc = sym.Variable(pre + "k_cache",
                          shape=(int(num_blocks), int(block_size), H, D))
        vc = sym.Variable(pre + "v_cache",
                          shape=(int(num_blocks), int(block_size), H, D))
        att = sym.contrib.PagedPrefillAttention(
            ln1, *_decode_trunk_vars(pre), kc, vc, table, lengths,
            num_heads=H, name=pre + "attn")
        x = x + att[0]
        new_kv += [att[1], att[2]]
        x = x + _decode_ffn(x, pre, d, ffn, moe_experts, moe_every, i)

    x = sym.LayerNorm(data=x, name="ln_f")
    last = sym.contrib.GatherTimestep(x, lengths - 1, name="last_token")
    logits = sym.FullyConnected(data=last, num_hidden=vocab, flatten=False,
                                name="lm_head")      # (B, vocab)
    if dtype in ("float16", "bfloat16"):
        logits = sym.Cast(data=logits, dtype="float32", name="cast_out")
    nxt = sym.argmax(logits, axis=1, name="greedy_token")
    return sym.Group([logits, nxt] + new_kv)


def get_mixed_step_symbol(num_classes=16384, num_layers=12, d_model=2048,
                          num_heads=16, ffn_dim=None, seq_len=1024,
                          dtype="float32", block_size=16, num_blocks=64,
                          moe_experts=0, moe_every=2, tensor_parallel=None,
                          **kwargs):
    """ONE decode iteration with chunked prefill fused in (stall-free
    scheduling, docs/DECODE.md): up to K prefill-chunk tokens of one
    admitted prompt AND one decode token for every active slot run in
    the same compiled, donated launch.

    Two streams share every parameter (each weight is created once as
    an explicit Variable and bound by both op instances, so the graph
    has ONE copy and checkpoints load unchanged):

    * decode stream — identical to `get_decode_step_symbol`: ``data``
      (C, 1), ``positions`` (C, 1) (< 0 = inactive), ``block_table``
      (C, M), PagedDecodeAttention per layer;
    * chunk stream — ``chunk_data`` (1, K) the current prompt chunk,
      ``chunk_positions`` (1, K) its absolute positions (for the
      position embedding), ``chunk_start`` (1,) / ``chunk_len`` (1,)
      the chunk's absolute offset and real token count
      (``chunk_len == 0`` disables the stream for the iteration), and
      ``chunk_table`` (1, M) the prefilling sequence's blocks;
      PagedChunkPrefillAttention attends the chunk causally against the
      cache prefix written by earlier chunks.

    Cache variables thread decode-write -> chunk-write per layer, so
    one donated buffer chain carries both streams.  K and C are set at
    bind time by the input shapes — the symbol itself is geometry-free.
    Outputs: ``[decode logits (C, vocab), decode greedy token (C,),
    chunk last-token logits (1, vocab), chunk greedy token (1,),
    new caches...]`` — the chunk head's greedy token is the sequence's
    FIRST generated token once its final chunk lands.

    ``tensor_parallel`` (docs/FLEET.md): a mesh-axis name (True means
    "mp") Megatron-splitting every dense layer exactly as in
    get_symbol(), PLUS head-sharding the paged KV caches over the axis
    — each device holds 1/mp of every cache block, so TP decode scales
    cache capacity with the mesh.  Annotations only; without a selected
    mesh the symbol binds replicated, unchanged.
    """
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    H = int(num_heads)
    D = d // H
    _col_w, _col_b, _row_w, _cache = _tp_attrs(tensor_parallel)

    data = sym.Variable("data")                      # (C, 1) token ids
    positions = sym.Variable("positions")            # (C, 1)
    table = sym.Variable("block_table")              # (C, M)
    cdata = sym.Variable("chunk_data")               # (1, K) chunk ids
    cpos = sym.Variable("chunk_positions")           # (1, K) absolute
    cstart = sym.Variable("chunk_start")             # (1,)
    clen = sym.Variable("chunk_len")                 # (1,)
    ctable = sym.Variable("chunk_table")             # (1, M)

    tokw = sym.Variable("tok_embed_weight")
    pos_w = sym.Variable("pos_embed_weight", shape=(1, int(seq_len), d))
    pos_flat = sym.Reshape(pos_w, shape=(int(seq_len), d))

    tok = sym.Embedding(data, tokw, input_dim=vocab, output_dim=d,
                        name="tok_embed")
    x = tok + sym.take(pos_flat, positions, name="pos_take")
    ctok = sym.Embedding(cdata, tokw, input_dim=vocab, output_dim=d,
                         name="c_tok_embed")
    xc = ctok + sym.take(pos_flat, cpos, name="c_pos_take")
    if dtype in ("float16", "bfloat16"):
        x = sym.Cast(data=x, dtype=dtype, name="cast_embed")
        xc = sym.Cast(data=xc, dtype=dtype, name="c_cast_embed")

    new_kv = []
    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        attn_vars = _decode_trunk_vars(pre, _col_w, _col_b, _row_w)
        ln1_g = sym.Variable(pre + "ln1_gamma")
        ln1_b = sym.Variable(pre + "ln1_beta", init=_init.Zero())
        kc = sym.Variable(pre + "k_cache",
                          shape=(int(num_blocks), int(block_size), H, D),
                          **_cache)
        vc = sym.Variable(pre + "v_cache",
                          shape=(int(num_blocks), int(block_size), H, D),
                          **_cache)

        ln1 = sym.LayerNorm(data=x, gamma=ln1_g, beta=ln1_b,
                            name=pre + "ln1")
        att = sym.contrib.PagedDecodeAttention(
            ln1, *attn_vars, kc, vc, table, positions,
            num_heads=H, name=pre + "attn")
        x = x + att[0]

        # the chunk reads/writes the cache AFTER the decode scatter —
        # one coherent donated buffer chain; block tables are disjoint
        # (a sequence is either prefilling or decoding, never both in
        # one launch), so the streams never alias a block
        cln1 = sym.LayerNorm(data=xc, gamma=ln1_g, beta=ln1_b,
                             name=pre + "c_ln1")
        catt = sym.contrib.PagedChunkPrefillAttention(
            cln1, *attn_vars, att[1], att[2], ctable, cstart, clen,
            num_heads=H, name=pre + "c_attn")
        xc = xc + catt[0]
        new_kv += [catt[1], catt[2]]

        shared = _ffn_shared_vars(pre, d, ffn, moe_experts, moe_every, i,
                                  _col_w, _col_b, _row_w)
        x = x + _decode_ffn(x, pre, d, ffn, moe_experts, moe_every, i,
                            shared=shared)
        xc = xc + _decode_ffn(xc, pre, d, ffn, moe_experts, moe_every, i,
                              shared=shared, tag="c_")

    lnf_g = sym.Variable("ln_f_gamma")
    lnf_b = sym.Variable("ln_f_beta", init=_init.Zero())
    lmw = sym.Variable("lm_head_weight")
    lmb = sym.Variable("lm_head_bias", init=_init.Zero())

    x = sym.LayerNorm(data=x, gamma=lnf_g, beta=lnf_b, name="ln_f")
    logits = sym.FullyConnected(data=x, weight=lmw, bias=lmb,
                                num_hidden=vocab, flatten=False,
                                name="lm_head")      # (C, 1, vocab)
    if dtype in ("float16", "bfloat16"):
        logits = sym.Cast(data=logits, dtype="float32", name="cast_out")
    flat = sym.Reshape(data=logits, shape=(-1, vocab), name="logits_2d")
    nxt = sym.argmax(flat, axis=1, name="greedy_token")

    xc = sym.LayerNorm(data=xc, gamma=lnf_g, beta=lnf_b, name="c_ln_f")
    clast = sym.contrib.GatherTimestep(xc, clen - 1, name="c_last_token")
    clogits = sym.FullyConnected(data=clast, weight=lmw, bias=lmb,
                                 num_hidden=vocab, flatten=False,
                                 name="c_lm_head")   # (1, vocab)
    if dtype in ("float16", "bfloat16"):
        clogits = sym.Cast(data=clogits, dtype="float32",
                           name="c_cast_out")
    cnxt = sym.argmax(clogits, axis=1, name="c_greedy_token")
    return sym.Group([flat, nxt, clogits, cnxt] + new_kv)


def get_spec_step_symbol(num_classes=16384, num_layers=12, d_model=2048,
                         num_heads=16, ffn_dim=None, seq_len=1024,
                         dtype="float32", block_size=16, num_blocks=64,
                         moe_experts=0, moe_every=2, tensor_parallel=None,
                         **kwargs):
    """The mixed step generalized to draft-verify spans (speculative
    decoding, docs/DECODE.md): instead of ONE token per slot, every
    iteration scores an S-token span per slot — the slot's last
    committed token followed by up to S-1 draft tokens — so the engine
    can accept several tokens from a single compiled, donated launch.

    The decode stream of `get_mixed_step_symbol` is replaced by a SPAN
    stream built on the same chunk-attention primitive the prompt
    chunk uses (``PagedChunkPrefillAttention`` is B-row capable:
    per-row start/length, zero-length rows are no-ops), batched across
    all C slots:

    * span stream — ``data`` (C, S) span token ids (row r holds the
      slot's last token then its draft; tail padded), ``positions``
      (C, S) absolute positions (pad rows 0 — harmless, masked by
      length), ``span_start`` (C,) each row's absolute cache offset,
      ``span_len`` (C,) real span tokens (0 = inactive slot),
      ``block_table`` (C, M); per layer the span scatters its K/V at
      positions ``span_start + j`` and attends causally against the
      slot's whole cache prefix — exactly verification: row j's logits
      condition on every committed token plus draft tokens < j;
    * chunk stream — unchanged from the mixed step (chunked prefill
      continues to ride along), reading the cache AFTER the span
      scatter in the same donated buffer chain.

    With S == 1 the span stream degenerates to exactly one token per
    slot — plain decoding through the chunk-attention primitive.
    Rejected draft rows leave K/V entries above the accepted prefix;
    they are dead by construction: the next iteration's span starts at
    the first rejected position and its scatter overwrites those rows
    before any query can attend them (scatter-then-gather inside the
    op, causal mask ``j <= pos``).

    Outputs: ``[span logits (C*S, vocab), span greedy tokens (C*S,),
    chunk last-token logits (1, vocab), chunk greedy token (1,),
    new caches...]`` — same base layout as the mixed step, so the
    engine's cache-commit and chunk-completion paths are shared.  Row
    ``r*S + j`` is slot r, span offset j; greedy token at offset j is
    the target model's choice for position ``span_start + j + 1``.

    ``tensor_parallel``: same Megatron split + head-sharded caches as
    get_mixed_step_symbol (docs/FLEET.md).
    """
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    H = int(num_heads)
    D = d // H
    _col_w, _col_b, _row_w, _cache = _tp_attrs(tensor_parallel)

    data = sym.Variable("data")                      # (C, S) span ids
    positions = sym.Variable("positions")            # (C, S) absolute
    sstart = sym.Variable("span_start")              # (C,)
    slen = sym.Variable("span_len")                  # (C,) 0 = inactive
    table = sym.Variable("block_table")              # (C, M)
    cdata = sym.Variable("chunk_data")               # (1, K) chunk ids
    cpos = sym.Variable("chunk_positions")           # (1, K) absolute
    cstart = sym.Variable("chunk_start")             # (1,)
    clen = sym.Variable("chunk_len")                 # (1,)
    ctable = sym.Variable("chunk_table")             # (1, M)

    tokw = sym.Variable("tok_embed_weight")
    pos_w = sym.Variable("pos_embed_weight", shape=(1, int(seq_len), d))
    pos_flat = sym.Reshape(pos_w, shape=(int(seq_len), d))

    tok = sym.Embedding(data, tokw, input_dim=vocab, output_dim=d,
                        name="tok_embed")
    x = tok + sym.take(pos_flat, positions, name="pos_take")
    ctok = sym.Embedding(cdata, tokw, input_dim=vocab, output_dim=d,
                         name="c_tok_embed")
    xc = ctok + sym.take(pos_flat, cpos, name="c_pos_take")
    if dtype in ("float16", "bfloat16"):
        x = sym.Cast(data=x, dtype=dtype, name="cast_embed")
        xc = sym.Cast(data=xc, dtype=dtype, name="c_cast_embed")

    new_kv = []
    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        attn_vars = _decode_trunk_vars(pre, _col_w, _col_b, _row_w)
        ln1_g = sym.Variable(pre + "ln1_gamma")
        ln1_b = sym.Variable(pre + "ln1_beta", init=_init.Zero())
        kc = sym.Variable(pre + "k_cache",
                          shape=(int(num_blocks), int(block_size), H, D),
                          **_cache)
        vc = sym.Variable(pre + "v_cache",
                          shape=(int(num_blocks), int(block_size), H, D),
                          **_cache)

        ln1 = sym.LayerNorm(data=x, gamma=ln1_g, beta=ln1_b,
                            name=pre + "ln1")
        att = sym.contrib.PagedChunkPrefillAttention(
            ln1, *attn_vars, kc, vc, table, sstart, slen,
            num_heads=H, name=pre + "attn")
        x = x + att[0]

        # chunk reads/writes the cache AFTER the span scatter — one
        # coherent donated chain; a sequence is either prefilling or
        # decoding, never both in one launch, so the streams never
        # alias a block (prefix-shared blocks are read-only in both)
        cln1 = sym.LayerNorm(data=xc, gamma=ln1_g, beta=ln1_b,
                             name=pre + "c_ln1")
        catt = sym.contrib.PagedChunkPrefillAttention(
            cln1, *attn_vars, att[1], att[2], ctable, cstart, clen,
            num_heads=H, name=pre + "c_attn")
        xc = xc + catt[0]
        new_kv += [catt[1], catt[2]]

        shared = _ffn_shared_vars(pre, d, ffn, moe_experts, moe_every, i,
                                  _col_w, _col_b, _row_w)
        x = x + _decode_ffn(x, pre, d, ffn, moe_experts, moe_every, i,
                            shared=shared)
        xc = xc + _decode_ffn(xc, pre, d, ffn, moe_experts, moe_every, i,
                              shared=shared, tag="c_")

    lnf_g = sym.Variable("ln_f_gamma")
    lnf_b = sym.Variable("ln_f_beta", init=_init.Zero())
    lmw = sym.Variable("lm_head_weight")
    lmb = sym.Variable("lm_head_bias", init=_init.Zero())

    x = sym.LayerNorm(data=x, gamma=lnf_g, beta=lnf_b, name="ln_f")
    logits = sym.FullyConnected(data=x, weight=lmw, bias=lmb,
                                num_hidden=vocab, flatten=False,
                                name="lm_head")      # (C, S, vocab)
    if dtype in ("float16", "bfloat16"):
        logits = sym.Cast(data=logits, dtype="float32", name="cast_out")
    flat = sym.Reshape(data=logits, shape=(-1, vocab), name="logits_2d")
    nxt = sym.argmax(flat, axis=1, name="greedy_token")

    xc = sym.LayerNorm(data=xc, gamma=lnf_g, beta=lnf_b, name="c_ln_f")
    clast = sym.contrib.GatherTimestep(xc, clen - 1, name="c_last_token")
    clogits = sym.FullyConnected(data=clast, weight=lmw, bias=lmb,
                                 num_hidden=vocab, flatten=False,
                                 name="c_lm_head")   # (1, vocab)
    if dtype in ("float16", "bfloat16"):
        clogits = sym.Cast(data=clogits, dtype="float32",
                           name="c_cast_out")
    cnxt = sym.argmax(clogits, axis=1, name="c_greedy_token")
    return sym.Group([flat, nxt, clogits, cnxt] + new_kv)


def get_draft_span_symbol(draft_k, num_classes=16384, num_layers=12,
                          d_model=2048, num_heads=16, ffn_dim=None,
                          seq_len=1024, dtype="float32", moe_experts=0,
                          moe_every=2, **kwargs):
    """ONE compiled program proposing ``draft_k`` greedy draft tokens
    (mx.speculative, docs/DECODE.md): the autoregressive draft loop of
    ``DraftModelDrafter`` — K full forwards, K argmax readbacks —
    unrolled into a single graph, so a proposal costs exactly one
    dispatch and one K-int readback whatever K is.

    Every unrolled iteration shares ONE copy of every weight (explicit
    Variables bound by all K trunk instances under per-iteration op-name
    tags — the mixed-step symbol's sharing pattern), and the draft
    checkpoint loads unchanged.  Inputs: ``data`` (1, seq_len) the
    left-aligned, zero-padded token history; ``length`` (1,) its real
    token count n; ``iota`` (1, seq_len) a runtime ``arange(seq_len)``
    (fed once — symbols have no shape-dependent constants).  Iteration
    j reads the hidden row at position ``n + j - 1``, takes the greedy
    token, and writes it back into ``data`` at position ``n + j`` via
    an iota-mask blend — token j+1 conditions on token j entirely
    on-device.  Output: the (draft_k,) proposed token ids — the ONE
    readback.  Rows past ``seq_len`` never arise: the drafter trims its
    context to ``seq_len - draft_k`` tokens before feeding.

    Causal masking keeps the padded tail invisible to every row that is
    read, so the proposals equal the sequential drafter's exactly.
    """
    vocab = int(num_classes)
    d = int(d_model)
    ffn = int(ffn_dim) if ffn_dim else 4 * d
    H = int(num_heads)
    S = int(seq_len)
    K = int(draft_k)
    if K < 1:
        raise ValueError("get_draft_span_symbol: draft_k must be >= 1")

    data = sym.Variable("data")                      # (1, S) history ids
    length = sym.Variable("length")                  # (1,) real count
    iota = sym.Variable("iota")                      # (1, S) arange(S)

    tokw = sym.Variable("tok_embed_weight")
    pos_w = sym.Variable("pos_embed_weight", shape=(1, S, d))
    lnf_g = sym.Variable("ln_f_gamma")
    lnf_b = sym.Variable("ln_f_beta", init=_init.Zero())
    lmw = sym.Variable("lm_head_weight")
    lmb = sym.Variable("lm_head_bias", init=_init.Zero())
    layers = []
    for i in range(int(num_layers)):
        pre = "layer%d_" % i
        layers.append({
            "pre": pre,
            "ln1_g": sym.Variable(pre + "ln1_gamma"),
            "ln1_b": sym.Variable(pre + "ln1_beta", init=_init.Zero()),
            "attn": _decode_trunk_vars(pre),
            "ffn": _ffn_shared_vars(pre, d, ffn, moe_experts, moe_every,
                                    i),
        })

    toks = []
    for j in range(K):
        tag = "d%d_" % j
        tok = sym.Embedding(data, tokw, input_dim=vocab, output_dim=d,
                            name=tag + "tok_embed")
        x = sym.broadcast_add(tok, pos_w, name=tag + "embed_add")
        if dtype in ("float16", "bfloat16"):
            x = sym.Cast(data=x, dtype=dtype, name=tag + "cast_embed")
        for i, ly in enumerate(layers):
            pre = ly["pre"]
            ln1 = sym.LayerNorm(data=x, gamma=ly["ln1_g"],
                                beta=ly["ln1_b"], name=pre + tag + "ln1")
            proj = sym.contrib.FusedCausalSelfAttention(
                ln1, *ly["attn"], num_heads=H, name=pre + tag + "attn")
            x = x + proj
            x = x + _decode_ffn(x, pre, d, ffn, moe_experts, moe_every,
                                i, shared=ly["ffn"], tag=tag)
        x = sym.LayerNorm(data=x, gamma=lnf_g, beta=lnf_b,
                          name=tag + "ln_f")
        # the greedy next token after n + j committed tokens lives in
        # row n + j - 1 (causal: it saw exactly the real history plus
        # drafts < j; the padded tail sits behind the mask)
        last = sym.contrib.GatherTimestep(x, length + (float(j) - 1.0),
                                          name=tag + "last_token")
        logits = sym.FullyConnected(data=last, weight=lmw, bias=lmb,
                                    num_hidden=vocab,
                                    name=tag + "lm_head")  # (1, vocab)
        if dtype in ("float16", "bfloat16"):
            logits = sym.Cast(data=logits, dtype="float32",
                              name=tag + "cast_out")
        nxt = sym.argmax(logits, axis=1, name=tag + "greedy")   # (1,)
        toks.append(nxt)
        if j + 1 < K:
            # scatter the token at position n + j: data += mask*(t - data)
            posj = sym.Reshape(length + float(j), shape=(1, 1),
                               name=tag + "pos2d")
            onehot = sym.broadcast_equal(iota, posj, name=tag + "onehot")
            tok2d = sym.Reshape(nxt, shape=(1, 1), name=tag + "tok2d")
            data = data + onehot * (tok2d - data)

    return sym.Concat(*toks, dim=0, name="draft_tokens")    # (K,)
