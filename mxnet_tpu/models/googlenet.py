"""GoogLeNet / Inception v1 (reference example/image-classification/symbols/googlenet.py)."""
from .. import symbol as sym


def conv_relu(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name="conv_%s" % name)
    return sym.Activation(data=conv, act_type="relu", name="relu_%s" % name)


def inception(data, n1x1, n3x3r, n3x3, n5x5r, n5x5, proj, name):
    c1 = conv_relu(data, n1x1, (1, 1), name="%s_1x1" % name)
    c3r = conv_relu(data, n3x3r, (1, 1), name="%s_3x3r" % name)
    c3 = conv_relu(c3r, n3x3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    c5r = conv_relu(data, n5x5r, (1, 1), name="%s_5x5r" % name)
    c5 = conv_relu(c5r, n5x5, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    pool = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                       pool_type="max", name="pool_%s" % name)
    cp = conv_relu(pool, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(c1, c3, c5, cp, name="concat_%s" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    body = conv_relu(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="1")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool1")
    body = conv_relu(body, 64, (1, 1), name="2r")
    body = conv_relu(body, 192, (3, 3), pad=(1, 1), name="2")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool2")
    body = inception(body, 64, 96, 128, 16, 32, 32, "3a")
    body = inception(body, 128, 128, 192, 32, 96, 64, "3b")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool3")
    body = inception(body, 192, 96, 208, 16, 48, 64, "4a")
    body = inception(body, 160, 112, 224, 24, 64, 64, "4b")
    body = inception(body, 128, 128, 256, 24, 64, 64, "4c")
    body = inception(body, 112, 144, 288, 32, 64, 64, "4d")
    body = inception(body, 256, 160, 320, 32, 128, 128, "4e")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool4")
    body = inception(body, 256, 160, 320, 32, 128, 128, "5a")
    body = inception(body, 384, 192, 384, 48, 128, 128, "5b")
    body = sym.Pooling(data=body, kernel=(7, 7), global_pool=True,
                       pool_type="avg", name="global_pool")
    body = sym.Flatten(data=body)
    body = sym.Dropout(data=body, p=0.4)
    fc = sym.FullyConnected(data=body, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(data=fc, name="softmax")
