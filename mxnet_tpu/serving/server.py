"""ModelServer: the public serving API + optional stdlib HTTP endpoint.

One object wires the subsystem together: a bounded ``RequestQueue``
(admission control), a shared ``DynamicBatcher`` (micro-batching +
bucket padding), and a ``ReplicaPool`` (one Predictor per device).

API surface::

    srv = ModelServer(sym, arg_params, aux_params,
                      input_shapes={"data": (3, 224, 224)},   # per example
                      num_replicas=2, max_batch_size=8)
    fut  = srv.submit({"data": x})            # future of [out_i rows]
    outs = srv.predict({"data": x})           # sync convenience
    outs = await srv.submit_async({"data": x})
    srv.drain(); srv.stop()
    srv.stats()                               # metrics snapshot (dict)
    srv.start_http(port=8123)                 # POST /predict, GET /stats

Observability: every snapshot field is also exported through
``mx.profiler`` user objects (Domain "serving": queue-depth and
batch-occupancy Counters, reject Markers), so a profiler trace shows the
serving control plane alongside the device timeline.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..base import MXNetError
from .. import profiler as _prof
from .batcher import (DeadlineExceededError, DynamicBatcher, QueueFullError,
                      Request, RequestQueue, ServerClosedError, ServingError,
                      normalize_buckets, percentile as _percentile)
from .replica import ReplicaPool

__all__ = ["ModelServer", "ServerStats"]

log = logging.getLogger(__name__)


class ServerStats:
    """Thread-safe metrics sink shared by the queue, batcher and replicas.

    Latency/throughput track a sliding window of recent completions (the
    last ``window`` requests), counters are monotonic totals. The same
    numbers feed ``stats()`` snapshots, the mx.profiler Counters, AND
    the mx.telemetry registry: every hook mirrors into process-wide
    ``serving_*`` series (docs/OBSERVABILITY.md), which is what ``GET
    /metrics`` scrapes. Registry series are shared across ModelServer
    instances and are never reset by :meth:`reset` (Prometheus
    counters must stay monotonic); per-instance ``stats()`` snapshots
    keep their window/reset semantics unchanged.
    """

    def __init__(self, window=4096):
        self._lock = threading.Lock()
        self.settled_cv = threading.Condition(self._lock)
        self.t_start = time.monotonic()
        # monotonic totals
        self.admitted = 0
        self.completed = 0
        self.rejected_queue_full = 0
        self.rejected_deadline = 0
        self.failed = 0
        self.cancelled = 0
        # batching
        self.batches = 0
        self.occupancy_sum = 0
        self.fill_sum = 0.0
        self.per_bucket = {}
        # sliding windows
        self._latencies = deque(maxlen=window)      # seconds
        self._completions = deque(maxlen=window)    # monotonic timestamps
        # profiler export (events only recorded while the profiler runs;
        # the Counters are registry-backed, so these five also appear in
        # /metrics as serving_queue_depth / serving_batch_occupancy / ...)
        dom = _prof.Domain("serving")
        self._c_depth = dom.new_counter("serving.queue_depth")
        self._c_occ = dom.new_counter("serving.batch_occupancy")
        self._c_p50 = dom.new_counter("serving.latency_p50_us")
        self._c_p99 = dom.new_counter("serving.latency_p99_us")
        self._c_qps = dom.new_counter("serving.throughput_qps")
        self._m_reject = dom.new_marker("serving.reject")
        # registry mirror: monotonic totals + the request-latency
        # histogram behind the /metrics scrape
        from .. import telemetry as _tm
        reg = _tm.REGISTRY
        self._r_admitted = reg.counter(
            "serving_admitted", "requests accepted into the queue")
        self._r_completed = reg.counter(
            "serving_completed", "requests completed successfully")
        self._r_rej_full = reg.counter(
            "serving_rejected_queue_full", "requests rejected: queue full")
        self._r_rej_deadline = reg.counter(
            "serving_rejected_deadline", "requests expired before running")
        self._r_failed = reg.counter(
            "serving_failed", "requests failed in a batch")
        self._r_cancelled = reg.counter(
            "serving_cancelled", "requests cancelled by the client")
        self._r_batches = reg.counter(
            "serving_batches", "micro-batches dispatched to replicas")
        self._r_latency = reg.histogram(
            "serving_request_ms",
            "end-to-end request latency (submit -> batch completion)",
            unit="ms")

    # -- hooks ---------------------------------------------------------
    def record_admitted(self, depth):
        with self._lock:
            self.admitted += 1
        self._r_admitted.inc()
        self._c_depth.set_value(depth)

    def record_depth(self, depth):
        self._c_depth.set_value(depth)

    def record_queue_full(self):
        with self._lock:
            self.rejected_queue_full += 1
        self._r_rej_full.inc()
        self._m_reject.mark()

    def record_expired(self, req):
        with self.settled_cv:
            self.rejected_deadline += 1
            self.settled_cv.notify_all()
        self._r_rej_deadline.inc()
        self._m_reject.mark()

    def record_cancelled(self, req):
        with self.settled_cv:
            self.cancelled += 1
            self.settled_cv.notify_all()
        self._r_cancelled.inc()

    def record_batch(self, replica_idx, mb):
        now = time.monotonic()
        done_latencies = []
        with self.settled_cv:
            self.batches += 1
            self.occupancy_sum += mb.n_real
            self.fill_sum += mb.fill
            self.per_bucket[mb.bucket] = self.per_bucket.get(mb.bucket, 0) + 1
            for req in mb.requests:
                if (req.future.done() and not req.future.cancelled()
                        and req.future.exception() is None):
                    self.completed += 1
                    self._latencies.append(now - req.t_submit)
                    self._completions.append(now)
                    done_latencies.append(now - req.t_submit)
            self.settled_cv.notify_all()
        self._r_batches.inc()
        if done_latencies:
            self._r_completed.inc(len(done_latencies))
            for lat in done_latencies:
                self._r_latency.observe(lat * 1e3)
        self._c_occ.set_value(mb.n_real)

    def record_failed_batch(self, replica_idx, mb, exc):
        with self.settled_cv:
            self.failed += mb.n_real
            self.settled_cv.notify_all()
        self._r_failed.inc(mb.n_real)

    def reset(self):
        """Zero every counter and window (benchmarks reset after warmup
        so compile-time batches don't bias occupancy/latency). Call only
        while the server is idle — an in-flight request would settle
        against the fresh counters and skew drain accounting."""
        with self.settled_cv:
            self.t_start = time.monotonic()
            self.admitted = self.completed = 0
            self.rejected_queue_full = self.rejected_deadline = 0
            self.failed = self.cancelled = 0
            self.batches = 0
            self.occupancy_sum = 0
            self.fill_sum = 0.0
            self.per_bucket = {}
            self._latencies.clear()
            self._completions.clear()
            self.settled_cv.notify_all()

    # -- drain support -------------------------------------------------
    def settled(self):
        return (self.completed + self.rejected_deadline + self.failed
                + self.cancelled)

    def wait_settled(self, target, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.settled_cv:
            while self.settled() < target:
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self.settled_cv.wait(left if left is not None else 0.1)
            return True

    # -- snapshot ------------------------------------------------------
    def snapshot(self, queue_depth=0, replicas=None):
        with self._lock:
            lat = sorted(self._latencies)
            comps = list(self._completions)
            batches = self.batches
            snap = {
                "uptime_s": round(time.monotonic() - self.t_start, 3),
                "queue_depth": queue_depth,
                "requests": {
                    "admitted": self.admitted,
                    "completed": self.completed,
                    "rejected_queue_full": self.rejected_queue_full,
                    "rejected_deadline": self.rejected_deadline,
                    "failed": self.failed,
                    "cancelled": self.cancelled,
                },
                "batches": {
                    "count": batches,
                    "mean_occupancy": (self.occupancy_sum / batches
                                       if batches else None),
                    "mean_fill": (self.fill_sum / batches
                                  if batches else None),
                    "per_bucket": dict(sorted(self.per_bucket.items())),
                },
            }
        to_ms = lambda v: None if v is None else round(v * 1e3, 3)
        snap["latency_ms"] = {
            "p50": to_ms(_percentile(lat, 0.50)),
            "p90": to_ms(_percentile(lat, 0.90)),
            "p99": to_ms(_percentile(lat, 0.99)),
            "mean": to_ms(sum(lat) / len(lat) if lat else None),
            "max": to_ms(lat[-1] if lat else None),
        }
        if len(comps) >= 2 and comps[-1] > comps[0]:
            snap["throughput_qps"] = round(
                (len(comps) - 1) / (comps[-1] - comps[0]), 2)
        else:
            snap["throughput_qps"] = None
        if replicas is not None:
            snap["replicas"] = replicas
        # mirror the derived metrics into the profiler counters so a
        # chrome trace carries p50/p99/qps tracks next to the per-batch
        # queue-depth/occupancy ones (events only record while running)
        if _prof.state() == "run":
            if snap["latency_ms"]["p50"] is not None:
                self._c_p50.set_value(snap["latency_ms"]["p50"] * 1e3)
                self._c_p99.set_value(snap["latency_ms"]["p99"] * 1e3)
            if snap["throughput_qps"] is not None:
                self._c_qps.set_value(snap["throughput_qps"])
        return snap


class ModelServer:
    """Dynamic-batching, multi-replica inference server (module docs).

    Parameters
    ----------
    symbol, arg_params, aux_params : the model (as for ``Predictor``)
    input_shapes : dict of per-EXAMPLE shapes, WITHOUT the batch axis —
        ``{"data": (3, 224, 224)}`` serves batches of (b, 3, 224, 224).
    num_replicas : worker replicas; replica i binds to ``contexts[i]``
        (default: ``mx.tpu(i)`` when accelerators exist, else ``mx.cpu(i)``)
    max_batch_size : micro-batch cap = the top bucket
    max_latency_ms : batching window opened by the first waiting request
    queue_capacity : admission bound; a full queue rejects immediately
    timeout_ms : default per-request deadline (None = no deadline)
    buckets : batch-size ladder (default 1, 2, 4, ..., max_batch_size)
    warmup : pre-compile every bucket shape at construction (threaded
        across (replica, bucket) pairs; MXNET_AOT_WARMUP_THREADS)
    warmup_manifest : AOT manifest path or dict (mx.aot.capture) — warm
        only the buckets a previous process actually served, marking
        their programs ``warmed`` in telemetry.programs(); with
        MXNET_COMPILE_CACHE_DIR set the warmup disk-loads instead of
        compiling (docs/AOT.md).  Default: the MXNET_AOT_MANIFEST knob.
    """

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 num_replicas=1, contexts=None, max_batch_size=8,
                 max_latency_ms=5.0, queue_capacity=None, timeout_ms=None,
                 dtype="float32", buckets=None, warmup=True,
                 warmup_manifest=None, decode_engine=None, fleet=None):
        from ..predictor import Predictor

        for name, shape in input_shapes.items():
            if not isinstance(shape, (tuple, list)):
                raise MXNetError("input_shapes[%r] must be a shape tuple "
                                 "(per example, no batch axis)" % name)
        self._example_shapes = {n: tuple(s) for n, s in input_shapes.items()}
        self._dtype = dtype
        self._timeout_ms = timeout_ms
        # one ladder for everyone: the batcher can emit any bucket in it,
        # so the replicas/warmup/top-bind must see the identical list —
        # including a max_batch_size cap the caller's ladder didn't reach
        # (otherwise the first full-load batch would compile mid-traffic)
        self._buckets = normalize_buckets(buckets, max_batch_size)
        if queue_capacity is None:
            queue_capacity = max(64, 4 * max_batch_size * num_replicas)
        self._queue = RequestQueue(queue_capacity)
        self._stats = ServerStats()
        self._batcher = DynamicBatcher(self._queue, max_batch_size,
                                       max_latency_ms, self._buckets)
        self._batcher.on_expired = self._stats.record_expired
        self._batcher.on_cancelled = self._stats.record_cancelled
        self._batcher.on_depth = self._stats.record_depth

        if contexts is None:
            contexts = self._default_contexts(num_replicas)
        if len(contexts) != num_replicas:
            raise MXNetError("need %d contexts, got %d"
                             % (num_replicas, len(contexts)))
        top = self._buckets[-1]

        def make_predictor(ctx):
            return Predictor(
                symbol, arg_params, aux_params,
                {n: (top,) + s for n, s in self._example_shapes.items()},
                ctx=ctx, dtype=dtype)

        # warmup runs through aot_warm below so construction and the
        # explicit mx.aot.warm path share one (threaded) code path
        self._pool = ReplicaPool(contexts, make_predictor, self._buckets,
                                 self._batcher, self._stats, warmup=False)
        if warmup_manifest is None:
            from .. import aot as _aot
            warmup_manifest = _aot.default_path()
        self._warmup_manifest = warmup_manifest
        if warmup_manifest is not None:
            self.aot_warm(warmup_manifest)
        elif warmup:
            self._pool.warmup()
        self._closed = False
        self._http = None
        self._http_thread = None
        # optional mx.decode generative engine: POST /generate streams
        # chunked JSON-lines through it, reload() hot-swaps its weights
        # in lockstep with the replicas (docs/DECODE.md). The caller
        # owns the engine's lifecycle; stop() does not stop it.
        self._decode_engine = decode_engine
        # optional mx.fleet router: /generate requests are PLACED by
        # prefix affinity across the router's decode replicas instead
        # of going to the single attached engine; a `session` field in
        # the request body rides the router's stickiness map
        # (docs/FLEET.md). The caller owns replica lifecycles.
        self._fleet = fleet
        # hot-reload bookkeeping (docs/CHECKPOINT.md): version of the
        # weights currently served (checkpoint tag / epoch), reload count
        self._model_version = None
        self._reloads = 0
        self._reload_lock = threading.Lock()
        from .. import telemetry as _tm
        self._r_reloads = _tm.REGISTRY.counter(
            "serving_reloads", "successful hot weight reloads")
        self._pool.start()

    # ------------------------------------------------------------------
    @staticmethod
    def _default_contexts(n):
        import jax
        from .. import context as _ctx
        if any(d.platform != "cpu" for d in jax.local_devices()):
            return [_ctx.tpu(i % _ctx.num_tpus()) for i in range(n)]
        return [_ctx.cpu(i) for i in range(n)]

    @classmethod
    def load(cls, prefix, epoch, input_shapes, **kwargs):
        """Build a server from ``prefix-symbol.json`` + ``prefix-%04d.params``
        (the MXPredCreate file form)."""
        from .. import model as _model
        sym, arg_params, aux_params = _model.load_checkpoint(prefix, epoch)
        return cls(sym, arg_params, aux_params, input_shapes, **kwargs)

    # ------------------------------------------------------------------
    def _normalize(self, inputs):
        if set(inputs) != set(self._example_shapes):
            raise MXNetError(
                "inputs must provide exactly %s (got %s)"
                % (sorted(self._example_shapes), sorted(inputs)))
        out = {}
        for name, value in inputs.items():
            if hasattr(value, "asnumpy"):     # NDArray
                value = value.asnumpy()
            try:
                arr = _np.asarray(value, dtype=self._dtype)
            except (TypeError, ValueError) as e:
                # keep the structured-error contract: a garbage payload
                # is a client error (HTTP 400), not an internal 500
                raise MXNetError("input %r: cannot convert to a %s array "
                                 "(%s)" % (name, self._dtype, e)) from e
            want = self._example_shapes[name]
            if arr.shape != want:
                raise MXNetError(
                    "input %r: expected per-example shape %s, got %s"
                    % (name, want, arr.shape))
            out[name] = arr
        return out

    def submit(self, inputs=None, timeout_ms=None, **kw_inputs):
        """Enqueue one example; returns a ``concurrent.futures.Future``
        resolving to ``[output_i_row, ...]`` (one numpy array per model
        output). Raises ``QueueFullError`` (backpressure) or
        ``ServerClosedError`` immediately; the future fails with
        ``DeadlineExceededError`` when the deadline expires first."""
        if inputs is None:
            inputs = kw_inputs
        elif kw_inputs:
            raise MXNetError("pass inputs as one dict or as kwargs, not both")
        if self._closed:
            raise ServerClosedError("server is stopped")
        arrays = self._normalize(inputs)
        timeout_ms = self._timeout_ms if timeout_ms is None else timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        fut = Future()
        req = Request(arrays, fut, deadline)
        from .. import telemetry as _tm
        if _tm.tracing.enabled():
            # admission -> settle span; parent = the submitting thread's
            # context (the HTTP handler's span, or a caller's trace)
            span = _tm.tracing.start_span("serving.request", rid=req.rid)
            req.span = span
            fut.add_done_callback(
                lambda f: span.end(
                    outcome=("cancelled" if f.cancelled() else
                             type(f.exception()).__name__
                             if f.exception() is not None else "ok")))
        try:
            self._queue.put(req)
        except QueueFullError:
            self._stats.record_queue_full()
            if req.span is not None:
                req.span.end(outcome="queue_full")
            raise
        self._stats.record_admitted(len(self._queue))
        return fut

    def predict(self, inputs=None, timeout_ms=None, **kw_inputs):
        """Synchronous convenience: submit + wait."""
        fut = self.submit(inputs, timeout_ms=timeout_ms, **kw_inputs)
        return fut.result()

    async def submit_async(self, inputs=None, timeout_ms=None, **kw_inputs):
        """Asyncio form: ``outs = await srv.submit_async({...})``."""
        import asyncio
        fut = self.submit(inputs, timeout_ms=timeout_ms, **kw_inputs)
        return await asyncio.wrap_future(fut)

    # ------------------------------------------------------------------
    def drain(self, timeout=None):
        """Block until everything admitted so far has settled (completed,
        expired, or failed). Returns False on timeout."""
        with self._stats._lock:
            target = self._stats.admitted
        return self._stats.wait_settled(target, timeout)

    def stop(self, drain=True, timeout=None):
        """Stop the server. ``drain=True`` (graceful) finishes queued work
        first; ``drain=False`` fails queued requests with
        ``ServerClosedError``. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.stop_http()
        if drain:
            self.drain(timeout)
            self._queue.close()
        else:
            self._queue.close()
            n_failed, n_raced = self._queue.reject_all(
                lambda req: ServerClosedError("server stopped before "
                                              "request %d ran" % req.rid))
            if n_failed or n_raced:
                with self._stats.settled_cv:
                    self._stats.failed += n_failed
                    self._stats.cancelled += n_raced
                    self._stats.settled_cv.notify_all()
                self._stats._r_failed.inc(n_failed)
                self._stats._r_cancelled.inc(n_raced)
        self._pool.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    def reload(self, prefix, tag=None, epoch=None):
        """Hot-swap every replica to newer weights WITHOUT dropping
        queued requests (docs/CHECKPOINT.md).

        ``prefix`` names an mx.checkpoint prefix: ``tag=None`` resolves
        the newest checksum-intact checkpoint via
        ``checkpoint.latest`` (a torn in-progress write is skipped, not
        an error); ``epoch`` instead loads a legacy
        ``prefix-%04d.params`` file directly. Params are validated
        against the bound model before any replica is touched, then
        swapped in place per replica under its forward lock — compiled
        executors, queue and in-flight batches all survive. Returns the
        version served (tag/epoch)."""
        from ..checkpoint import resolve_params
        with self._reload_lock:
            arg_params, aux_params, version = resolve_params(
                prefix, tag, epoch, what="reload")
            base = self._pool.replicas[0]._base
            missing = [n for n in base._exe.arg_dict
                       if n not in arg_params
                       and n not in self._example_shapes
                       and not n.endswith("label")]
            missing += [n for n in base._exe.aux_dict
                        if n not in (aux_params or {})]
            if missing:
                raise MXNetError("reload: checkpoint is missing params %s"
                                 % sorted(missing))
            # shape-validate EVERYTHING before any replica is touched:
            # a mid-swap failure would leave replicas half-swapped with
            # no rollback, corrupting live traffic
            bad = []
            for params, live in ((arg_params, base._exe.arg_dict),
                                 (aux_params or {}, base._exe.aux_dict)):
                for name, v in params.items():
                    dst = live.get(name)
                    if dst is None or name in self._example_shapes:
                        continue
                    shape = getattr(v, "shape", None)
                    if shape is None:
                        shape = _np.shape(v)
                    if tuple(shape) != tuple(dst.shape):
                        bad.append(name)
            if bad:
                raise MXNetError(
                    "reload: checkpoint shapes do not match the bound "
                    "model for %s" % sorted(bad))
            from ..ndarray import NDArray
            arg_params = {k: v if isinstance(v, NDArray)
                          else NDArray(_np.asarray(v))
                          for k, v in arg_params.items()}
            aux_params = {k: v if isinstance(v, NDArray)
                          else NDArray(_np.asarray(v))
                          for k, v in (aux_params or {}).items()}
            # the attached decode engine must accept the checkpoint too
            # (same architecture => its paged-cache layout is preserved);
            # validate BEFORE any replica swaps so a mismatch is a clean
            # 409 with zero state touched
            if self._decode_engine is not None:
                self._decode_engine.check_params(arg_params)
            for rep in self._pool.replicas:
                rep.swap_params(arg_params, aux_params)
            if self._decode_engine is not None:
                self._decode_engine.swap_params(arg_params, version=version)
            self._model_version = version
            self._reloads += 1
            self._r_reloads.inc()
            return version

    # ------------------------------------------------------------------
    def _resolve_manifest(self, manifest):
        """Load + compatibility-gate an AOT manifest.  Incompatible or
        mismatched manifests resolve to None (full cold warmup) — a
        stale manifest must never fail a deploy (docs/AOT.md)."""
        from .. import aot as _aot
        m = manifest if manifest is not None else self._warmup_manifest
        if isinstance(m, str):
            try:
                m = _aot.load(m)
            except MXNetError as e:
                log.warning("serving: ignoring AOT manifest (%s)", e)
                return None
        if m is not None:
            ok, reason = _aot.compatible(m)
            if not ok:
                log.warning("serving: AOT manifest incompatible (%s); "
                            "warming the full bucket ladder instead",
                            reason)
                return None
        return m

    def aot_warm(self, manifest=None):
        """Compile (or, with MXNET_COMPILE_CACHE_DIR, disk-load) every
        (replica, bucket) program BEFORE the server accepts traffic —
        the mx.aot warmup hook (docs/AOT.md).  ``manifest`` defaults to
        the server's ``warmup_manifest``; programs dispatched here are
        flagged ``warmed`` in telemetry.programs().  Returns the number
        of programs dispatched."""
        from ..telemetry import programs as _programs
        m = self._resolve_manifest(manifest)
        with _programs.warming():
            return self._pool.warmup(manifest=m)

    def add_replica(self, ctx=None):
        """Scale up by one replica.  The new replica binds, AOT-warms
        its bucket ladder (through the server's manifest and the
        persistent cache, like startup) and only THEN starts pulling
        from the shared batcher — scale-up traffic never lands on a
        compiling replica.  Returns the new replica's index."""
        from ..telemetry import programs as _programs
        with self._reload_lock:
            if self._closed:
                raise MXNetError("cannot add a replica to a stopped server")
            if ctx is None:
                n = len(self._pool.replicas)
                ctx = self._default_contexts(n + 1)[n]
            m = self._resolve_manifest(None)
            with _programs.warming():
                rep = self._pool.add_replica(ctx, manifest=m)
            return rep.index

    # ------------------------------------------------------------------
    def stats(self):
        """Metrics snapshot: queue depth, admission/served counters, batch
        occupancy, latency percentiles, throughput, per-replica detail
        (glossary in docs/SERVING.md)."""
        snap = self._stats.snapshot(queue_depth=len(self._queue),
                                    replicas=self._pool.snapshot())
        snap["model_version"] = self._model_version
        # per-instance count; the registry's serving_reloads series is
        # process-global and shared across servers
        snap["reloads"] = self._reloads
        if self._decode_engine is not None:
            snap["decode"] = self._decode_engine.stats()
        if self._fleet is not None:
            snap["fleet"] = self._fleet.stats()
        return snap

    def reset_stats(self):
        """Zero the metrics (e.g. after a warmup phase); the server must
        be idle — drain() first if unsure."""
        self._stats.reset()

    # ------------------------------------------------------------------
    # optional JSON-over-HTTP endpoint (stdlib only)
    # ------------------------------------------------------------------
    def start_http(self, port=8123, host="127.0.0.1"):
        """Serve ``POST /predict`` ({"inputs": {...}, "timeout_ms": n}),
        ``GET /stats``, ``GET /metrics`` (Prometheus text exposition of
        the whole mx.telemetry registry — serving, kvstore, fit-step and
        HBM series; docs/OBSERVABILITY.md), ``GET /pod_metrics`` (the
        aggregated fleet view — rank-labeled scalars, bucket-merged
        histograms) and ``GET /health`` (which carries any open
        sentinel SLO incidents) on a daemon thread. Returns the bound
        (host, port)."""
        if self._http is not None:
            raise MXNetError("HTTP endpoint already running")
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from .. import telemetry as _tm

        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 for chunked transfer on /generate; every other
            # reply carries an exact Content-Length, so keep-alive is
            # safe.  The timeout reaps idle persistent connections —
            # without it every keep-alive client pins a server thread
            # and fd forever
            protocol_version = "HTTP/1.1"
            timeout = 60

            def log_message(self, *a):   # keep pytest/console output clean
                pass

            def _reply(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self):
                """Parse the POST body; replies 400 and returns None
                when it isn't a JSON object (callers just return)."""
                n = int(self.headers.get("Content-Length", 0) or 0)
                try:
                    doc = json.loads(self.rfile.read(n) or b"{}")
                except ValueError as e:
                    self._reply(400, {"error": "invalid JSON: %s" % e,
                                      "type": "bad_request"})
                    return None
                if not isinstance(doc, dict):
                    self._reply(400, {"error": "body must be a JSON "
                                      "object", "type": "bad_request"})
                    return None
                return doc

            def _chunk(self, data):
                self.wfile.write(b"%x\r\n" % len(data))
                self.wfile.write(data)
                self.wfile.write(b"\r\n")
                self.wfile.flush()

            def _do_generate(self, doc):
                """POST /generate — streamed autoregressive generation
                through the attached mx.decode engine.  Body:
                ``{"tokens": [...], "max_new_tokens": n, "stream": true,
                "eos_id"/"temperature"/"timeout_ms"/"seed": optional,
                "speculative": false}`` — the last opts one request out
                of draft-verify spans on a spec-enabled engine
                (docs/DECODE.md).
                Streaming replies are chunked JSON-lines: one
                ``{"index": i, "token": t}`` object per generated token
                and a final ``{"done": true, ...}`` summary line (an
                in-flight failure becomes a ``{"done": true, "error":
                ...}`` tail instead of a broken connection)."""
                eng = server._decode_engine
                if eng is None and server._fleet is None:
                    self._reply(404, {"error": "no decode engine attached "
                                      "(ModelServer(decode_engine=...) or "
                                      "ModelServer(fleet=...))",
                                      "type": "no_decode"})
                    return
                tokens = doc.get("tokens")
                if not isinstance(tokens, list) or not tokens:
                    self._reply(400, {"error": "generate needs a non-empty "
                                      "'tokens' list", "type": "bad_request"})
                    return
                replica = None
                if server._fleet is not None:
                    # cache-aware placement: the router picks the
                    # replica whose prefix trie best matches the
                    # prompt; a `session` field pins a conversation to
                    # the replica that holds its history (docs/FLEET.md)
                    try:
                        replica, eng = server._fleet.route(
                            tokens, session=doc.get("session"))
                    except MXNetError as e:
                        self._reply(503, {"error": str(e),
                                          "type": "no_replicas"})
                        return
                kwargs = {}
                if "eos_id" in doc:
                    kwargs["eos_id"] = doc["eos_id"]
                try:
                    handle = eng.submit(
                        tokens,
                        max_new_tokens=doc.get("max_new_tokens"),
                        timeout_ms=doc.get("timeout_ms"),
                        temperature=float(doc.get("temperature", 0.0)),
                        seed=doc.get("seed"),
                        speculative=bool(doc.get("speculative", True)),
                        **kwargs)
                except QueueFullError as e:
                    self._reply(429, {"error": str(e), "type": "queue_full"})
                    return
                except ServerClosedError as e:
                    self._reply(503, {"error": str(e), "type": "closed"})
                    return
                except (MXNetError, TypeError, ValueError) as e:
                    # TypeError/ValueError: malformed field types
                    # (non-int tokens, non-numeric temperature) — a
                    # client error, same as any other validation miss.
                    # MXNetError here is only a prompt the cache can
                    # NEVER hold (>= max_context, or more blocks than
                    # exist): long-but-servable prompts are admitted
                    # and prefilled in chunks (docs/DECODE.md)
                    self._reply(400, {"error": str(e), "type": "bad_request"})
                    return
                if not doc.get("stream", True):
                    # a client-supplied timeout_ms is enforced BY THE
                    # ENGINE (DeadlineExceededError below); the server
                    # backstop only has to outlast it, it must never
                    # undercut an explicit longer deadline
                    t_ms = doc.get("timeout_ms")
                    wait_s = 600.0 if t_ms is None else t_ms / 1e3 + 30.0
                    try:
                        toks = handle.result(timeout=wait_s)
                    except DeadlineExceededError as e:
                        self._reply(504, {"error": str(e),
                                          "type": "deadline"})
                        return
                    except TimeoutError as e:
                        # server-side backstop tripped: stop generating
                        # into a handle nobody will read (frees the
                        # slot + cache blocks at the next iteration)
                        handle.cancel()
                        self._reply(504, {"error": str(e),
                                          "type": "deadline"})
                        return
                    except Exception as e:   # noqa: BLE001
                        self._reply(500, {"error": str(e),
                                          "type": "internal"})
                        return
                    body = {"tokens": toks,
                            "finish_reason": handle.finish_reason,
                            "ttft_ms": handle.ttft_ms}
                    if replica is not None:
                        body["replica"] = replica
                    self._reply(200, body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                tail = None
                try:
                    for i, tok in enumerate(handle):
                        try:
                            self._chunk((json.dumps(
                                {"index": i, "token": tok}) + "\n").encode())
                        except OSError:
                            # client went away mid-stream: release the
                            # slot + cache blocks instead of generating
                            # the rest into a queue nobody reads
                            handle.cancel()
                            return
                except Exception as e:   # noqa: BLE001 — error as a tail line
                    tail = {"done": True, "error": str(e),
                            "type": e.__class__.__name__,
                            "tokens": handle.tokens}
                if tail is None:
                    tail = {"done": True,
                            "finish_reason": handle.finish_reason,
                            "tokens": handle.tokens,
                            "ttft_ms": handle.ttft_ms}
                if replica is not None:
                    tail["replica"] = replica
                try:
                    self._chunk((json.dumps(tail) + "\n").encode())
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    handle.cancel()

            def do_GET(self):
                if self.path == "/metrics":
                    body = _tm.generate_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     _tm.export.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/pod_metrics":
                    # the aggregated fleet view (rank-labeled gauges/
                    # counters, bucket-merged histograms) — the local
                    # view when no exchange has happened yet
                    body = _tm.aggregate.pod_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     _tm.export.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/stats":
                    self._reply(200, server.stats())
                elif self.path == "/fleet":
                    if server._fleet is None:
                        self._reply(404, {"error": "no fleet router "
                                          "attached (ModelServer("
                                          "fleet=...))", "type": "no_fleet"})
                    else:
                        self._reply(200, server._fleet.stats())
                elif self.path == "/health":
                    alerts = _tm.sentinel.SENTINEL.active()
                    ok = not server._closed
                    self._reply(200 if ok else 503,
                                {"status": "ok" if ok else "stopped",
                                 "sentinel_alerts": alerts})
                else:
                    self._reply(404, {"error": "unknown path %s" % self.path})

            def do_POST(self):
                if self.path == "/generate":
                    try:
                        doc = self._read_json()
                        if doc is not None:
                            # W3C traceparent joins the caller's trace;
                            # the span parents the whole decode
                            # lifecycle submitted inside it
                            with _tm.tracing.span(
                                    "http.generate",
                                    parent=_tm.tracing.extract(
                                        self.headers) or "current"):
                                self._do_generate(doc)
                    except Exception as e:   # noqa: BLE001
                        self._reply(500, {"error": str(e),
                                          "type": "internal"})
                    return
                if self.path == "/reload":
                    # admin endpoint: swap replicas to a newer checkpoint
                    # ({"prefix": ..., "tag"|"epoch": optional})
                    try:
                        doc = self._read_json()
                        if doc is None:
                            return
                        if not doc.get("prefix"):
                            self._reply(400, {"error": "reload needs a "
                                              "'prefix'",
                                              "type": "bad_request"})
                            return
                        version = server.reload(doc["prefix"],
                                                tag=doc.get("tag"),
                                                epoch=doc.get("epoch"))
                        self._reply(200, {"status": "ok",
                                          "model_version": version})
                    except MXNetError as e:
                        self._reply(409, {"error": str(e),
                                          "type": "reload_failed"})
                    except Exception as e:   # noqa: BLE001
                        self._reply(500, {"error": str(e),
                                          "type": "internal"})
                    return
                if self.path != "/predict":
                    # HTTP/1.1 keep-alive: drain the unread body first
                    # or its bytes desynchronize the next request on
                    # this connection
                    self.rfile.read(int(self.headers.get("Content-Length",
                                                         0) or 0))
                    self._reply(404, {"error": "unknown path %s" % self.path})
                    return
                try:
                    doc = self._read_json()
                    if doc is None:
                        return
                    with _tm.tracing.span(
                            "http.predict",
                            parent=_tm.tracing.extract(self.headers)
                            or "current"):
                        fut = server.submit(
                            doc.get("inputs") or {},
                            timeout_ms=doc.get("timeout_ms"))
                        outs = fut.result()
                    self._reply(200, {"outputs": [o.tolist() for o in outs]})
                except QueueFullError as e:
                    self._reply(429, {"error": str(e), "type": "queue_full"})
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e), "type": "deadline"})
                except ServerClosedError as e:
                    self._reply(503, {"error": str(e), "type": "closed"})
                except ServingError as e:
                    self._reply(400, {"error": str(e), "type": "bad_request"})
                except MXNetError as e:
                    self._reply(400, {"error": str(e), "type": "bad_request"})
                except Exception as e:   # noqa: BLE001 — surface, don't hang
                    self._reply(500, {"error": str(e), "type": "internal"})

        self._http = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, name="mx-serving-http",
            daemon=True)
        self._http_thread.start()
        return self._http.server_address

    def stop_http(self):
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
            self._http_thread = None
