"""mx.serving — dynamic-batching, multi-replica TPU inference serving.

The capability the MXNet ecosystem shipped as MXNet Model Server, built
TPU-native on top of ``mx.predictor.Predictor``: an in-process
``ModelServer`` that coalesces single-example requests behind a bounded
queue into micro-batches (Clipper-style adaptive batching, NSDI '17),
pads them to a fixed ladder of batch-size buckets so every forward hits
an already-compiled XLA executable (no per-request recompiles — the
shape-bucketing insight continuous-batching systems build on), and
dispatches to N replica workers, each owning a ``Predictor`` bound to
its own device context.

Quickstart::

    import mxnet_tpu as mx
    from mxnet_tpu.serving import ModelServer

    srv = ModelServer.load("model", epoch=9,
                           input_shapes={"data": (3, 224, 224)},  # per example
                           num_replicas=2, max_batch_size=8,
                           max_latency_ms=5.0)
    fut = srv.submit({"data": img})          # -> concurrent.futures.Future
    probs = fut.result()[0]                  # list of per-output numpy rows
    print(srv.stats())                       # p50/p99, occupancy, qps, depth
    srv.stop()

See docs/SERVING.md for the full knob table and metrics glossary.
"""
from .batcher import (ServingError, QueueFullError, DeadlineExceededError,
                      ServerClosedError, Request, RequestQueue,
                      DynamicBatcher, MicroBatch, bucketize, default_buckets)
from .replica import Replica, ReplicaPool
from .server import ModelServer, ServerStats

__all__ = [
    "ModelServer", "ServerStats",
    "Replica", "ReplicaPool",
    "Request", "RequestQueue", "DynamicBatcher", "MicroBatch",
    "ServingError", "QueueFullError", "DeadlineExceededError",
    "ServerClosedError", "bucketize", "default_buckets",
]
