"""Bounded request queue + dynamic micro-batcher with bucket padding.

The admission path is deliberately synchronous and cheap: ``submit`` either
enqueues or fails *immediately* (``QueueFullError``) — backpressure is a
structured error the client can retry against, never unbounded memory
growth. Batching is adaptive (Clipper, NSDI '17): the first waiting
request opens a batching window of at most ``max_latency_ms``; the window
closes early the moment ``max_batch_size`` requests are waiting, so an
idle server adds at most one window of latency and a loaded server runs
full buckets back to back.

Bucket padding keeps the XLA jit cache warm: a batch of n requests is
padded up to the smallest bucket in the ladder (1, 2, 4, ..., max) by
replicating the first row. Every forward therefore runs one of
log2(max)+1 compiled shapes — never a fresh compile mid-traffic — and the
padded rows are sliced off before results are delivered, so padding can
never leak into outputs.
"""
from __future__ import annotations

import itertools
import threading
import time

import numpy as _np

from ..base import MXNetError

__all__ = ["ServingError", "QueueFullError", "DeadlineExceededError",
           "ServerClosedError", "Request", "RequestQueue", "DynamicBatcher",
           "MicroBatch", "bucketize", "default_buckets", "percentile"]


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted sequence (None when
    empty) — the one definition shared by ServerStats and decode
    stats()."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ServingError(MXNetError):
    """Base class for structured serving errors."""


class QueueFullError(ServingError):
    """Admission control: the request queue is at capacity; retry later."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired before a forward slot ran it."""


class ServerClosedError(ServingError):
    """The server is stopped (or stopping) and accepts no new work."""


def settle_exception(future, exc):
    """Fail a future, tolerating a client cancel racing us. Returns True
    when the exception landed, False when the future was already settled
    (cancelled/raced) — callers route accounting on it so every request
    settles exactly once and a lost race can never raise into (and kill)
    a replica worker loop."""
    try:
        future.set_exception(exc)
        return True
    except Exception:
        return False


def default_buckets(max_batch_size):
    """Power-of-two ladder 1, 2, 4, ..., capped and topped by max."""
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return out


def normalize_buckets(buckets, max_batch_size):
    """The ONE ladder-normalization rule, shared by ModelServer and
    DynamicBatcher so the shapes the batcher emits and the shapes the
    replicas warm can never diverge: sorted, deduped, topped up to
    max_batch_size."""
    if not buckets:
        return default_buckets(max_batch_size)
    out = sorted(set(int(b) for b in buckets))
    if out[0] < 1:
        raise MXNetError("buckets must be >= 1 (got %s)" % out)
    if out[-1] > max_batch_size:
        # an oversized bucket would pad EVERY batch past the cap — the
        # batcher never takes more than max_batch_size real requests
        raise MXNetError("bucket %d exceeds max_batch_size %d"
                         % (out[-1], max_batch_size))
    if out[-1] < max_batch_size:
        out.append(max_batch_size)
    return out


def bucketize(n, buckets):
    """Smallest bucket >= n (buckets sorted ascending)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


class Request:
    """One single-example inference request riding the queue."""
    __slots__ = ("rid", "inputs", "future", "deadline", "t_submit",
                 "span")
    _ids = itertools.count()

    def __init__(self, inputs, future, deadline=None):
        self.rid = next(Request._ids)
        self.inputs = inputs          # {name: per-example numpy array}
        self.future = future          # concurrent.futures.Future
        self.deadline = deadline      # monotonic seconds, or None
        self.t_submit = time.monotonic()
        # mx.trace span covering admission -> settle (None when tracing
        # is off); opened by ModelServer.submit, ended by the future's
        # done callback — the request's identity across queue/batcher/
        # replica threads
        self.span = None

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)


class MicroBatch:
    """A dequeued, padded batch ready for one forward."""
    __slots__ = ("requests", "arrays", "bucket", "n_real")

    def __init__(self, requests, arrays, bucket):
        self.requests = requests      # the n_real live requests, in order
        self.arrays = arrays          # {name: (bucket,)+shape numpy}
        self.bucket = bucket
        self.n_real = len(requests)

    @property
    def occupancy(self):
        """Real requests per executed forward (the acceptance metric)."""
        return self.n_real

    @property
    def fill(self):
        """Fraction of the bucket carrying real work."""
        return self.n_real / float(self.bucket)


class RequestQueue:
    """Bounded FIFO with immediate-reject admission control.

    All waits are predicate-loop waits on one Condition; ``close()``
    wakes every waiter, so no consumer can block past shutdown — the
    deadlock-freedom contract tests/test_serving.py exercises under
    concurrent clients.
    """

    def __init__(self, capacity):
        if capacity < 1:
            raise MXNetError("queue capacity must be >= 1 (got %d)" % capacity)
        self._capacity = capacity
        self._items = []
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self):
        with self._lock:
            return len(self._items)

    @property
    def capacity(self):
        return self._capacity

    @property
    def closed(self):
        return self._closed

    def put(self, req):
        """Enqueue or raise immediately — never blocks the submitter."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("server is stopped")
            if len(self._items) >= self._capacity:
                raise QueueFullError(
                    "request queue full (%d/%d); retry with backoff"
                    % (len(self._items), self._capacity))
            self._items.append(req)
            self._nonempty.notify()

    def wait_first(self, poll_s=0.05):
        """Block until an item is available or the queue closes. Returns
        True when items are waiting, False on close-and-drained."""
        with self._lock:
            while not self._items:
                if self._closed:
                    return False
                self._nonempty.wait(poll_s)
            return True

    def take(self, max_n):
        """Pop up to ``max_n`` items (possibly zero; never blocks)."""
        with self._lock:
            got = self._items[:max_n]
            del self._items[:max_n]
            return got

    def close(self):
        """Stop admitting; wake all waiting consumers. Items already
        queued remain takeable so a graceful drain can finish them."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    def reject_all(self, exc_factory):
        """Fail every queued request (non-graceful stop path). Returns
        (n_failed, n_raced) — raced = already settled/cancelled."""
        with self._lock:
            items, self._items = self._items, []
        n_failed = 0
        for req in items:
            if settle_exception(req.future, exc_factory(req)):
                n_failed += 1
        return n_failed, len(items) - n_failed


class DynamicBatcher:
    """Coalesce queued requests into bucket-padded micro-batches.

    Shared by all replica workers: each idle worker calls
    ``next_batch()``, so dispatch is least-loaded by construction (only a
    replica with a free forward slot ever pulls work — a busy replica
    never has a batch assigned to it while an idle peer waits).
    """

    def __init__(self, queue, max_batch_size, max_latency_ms, buckets=None):
        if max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        self.queue = queue
        self.max_batch_size = max_batch_size
        self.max_latency_s = max(0.0, float(max_latency_ms)) / 1e3
        self.buckets = normalize_buckets(buckets, max_batch_size)
        # server-installed stats hooks (req) -> None; drain() counts on
        # every admitted request reaching exactly one settled hook.
        # on_depth(depth) fires after every dequeue so the queue-depth
        # observable falls when the queue drains, not only on admits
        self.on_expired = None
        self.on_cancelled = None
        self.on_depth = None

    # ------------------------------------------------------------------
    def _expire(self, requests):
        """Split out expired/cancelled requests, failing their futures
        before they waste a forward slot."""
        live = []
        now = time.monotonic()
        for req in requests:
            if req.future.cancelled():
                if self.on_cancelled is not None:
                    self.on_cancelled(req)
                continue
            if req.expired(now):
                landed = settle_exception(req.future, DeadlineExceededError(
                    "request %d deadline expired after %.1f ms in queue"
                    % (req.rid, (now - req.t_submit) * 1e3)))
                hook = self.on_expired if landed else self.on_cancelled
                if hook is not None:
                    hook(req)
                continue
            live.append(req)
        return live

    def next_batch(self, poll_s=0.05):
        """Block until a micro-batch is ready; None when closed+drained.

        The batching window: the first request opens it; it closes when
        ``max_batch_size`` requests are waiting or ``max_latency_ms``
        elapsed — whichever is first.
        """
        while True:
            if not self.queue.wait_first(poll_s):
                return None
            t_open = time.monotonic()
            # the window: sleep in short slices so a burst arriving right
            # after the first request still closes the window early
            while (len(self.queue) < self.max_batch_size
                   and time.monotonic() - t_open < self.max_latency_s):
                time.sleep(min(0.001, self.max_latency_s / 4 or 0.001))
            requests = self._expire(self.queue.take(self.max_batch_size))
            if self.on_depth is not None:
                self.on_depth(len(self.queue))
            if requests:
                return self._pad(requests)
            # everything taken had expired — go back to waiting

    # ------------------------------------------------------------------
    def _pad(self, requests):
        bucket = bucketize(len(requests), self.buckets)
        names = requests[0].inputs.keys()
        arrays = {}
        for name in names:
            rows = [req.inputs[name] for req in requests]
            stacked = _np.stack(rows, axis=0)
            if bucket > len(rows):
                # replicate row 0 into the padding slots: real values keep
                # the numerics in-range (an all-zero pad can produce inf/
                # nan in ops like log-softmax whose rows are independent
                # anyway), and the rows are sliced off before delivery
                pad = _np.broadcast_to(
                    stacked[:1], (bucket - len(rows),) + stacked.shape[1:])
                stacked = _np.concatenate([stacked, pad], axis=0)
            arrays[name] = stacked
        return MicroBatch(requests, arrays, bucket)
