"""Replica workers: one Predictor per device context, bucketed executors.

Each ``Replica`` owns a ``Predictor`` bound to its own context —
``mx.tpu(i)`` in production, ``mx.cpu(i)`` under the test mesh — and a
ladder of bucket-shaped rebinds of it created through
``Predictor.reshape``, which shares the device-resident parameters
(executor-level reuse; no per-bucket host->device weight copy). The jit
cache is per *symbol*, so all replicas and all buckets share one trace
cache and each (bucket, device) pair compiles exactly once.

Dispatch is least-loaded by construction: every replica runs a pull loop
against the shared ``DynamicBatcher``, and only a replica with a free
forward slot pulls — a busy replica never queues work while an idle peer
waits. Per-replica in-flight/served counters feed ``ModelServer.stats()``.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

from .batcher import DeadlineExceededError, settle_exception

__all__ = ["Replica", "ReplicaPool", "manifest_buckets"]


def _warmup_threads(n_jobs):
    """Warmup pool width: ``MXNET_AOT_WARMUP_THREADS`` caps it, 0/unset
    means one thread per (replica, bucket) job up to 8.  Bucket shapes
    are distinct jit cache keys, so concurrent warmup compiles each
    exactly once — never the same entry twice."""
    try:
        n = int(os.environ.get("MXNET_AOT_WARMUP_THREADS", "0") or 0)
    except ValueError:
        n = 0
    if n <= 0:
        n = min(n_jobs, 8)
    return max(1, min(n, n_jobs))


def _run_warmup(jobs):
    """Drive (replica, bucket) warmup jobs through a thread pool (the
    pre-PR serial loop paid sum-of-compile-times at startup).  The
    AOT-warming flag is thread-local, so each worker re-enters the
    submitting thread's ``warming()`` phase."""
    if not jobs:
        return 0
    from ..telemetry import programs as _programs
    warmed = _programs.is_warming()

    def one(rep, bucket):
        if warmed:
            with _programs.warming():
                rep.warm_bucket(bucket)
        else:
            rep.warm_bucket(bucket)

    if len(jobs) == 1:
        one(*jobs[0])
        return 1
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=_warmup_threads(len(jobs)),
                            thread_name_prefix="mx-warmup") as pool:
        futures = [pool.submit(one, rep, b) for rep, b in jobs]
        for f in futures:
            f.result()       # propagate the first compile failure
    return len(jobs)


def manifest_buckets(entries, input_shapes, buckets):
    """Buckets an AOT manifest actually compiled for this model: bucket
    ``b`` matches when an executor-site program took an argument shaped
    ``(b,) + input_trailing_dims``.  Empty result means the manifest
    covers a different model — callers fall back to warming the full
    ladder rather than serving cold buckets."""
    buckets = set(buckets)
    trailing = {tuple(shape)[1:] for shape in input_shapes.values()}
    found = set()
    for e in entries:
        if e.get("site") != "executor":
            continue
        for spec in e.get("arg_specs") or ():
            if not spec:
                continue
            shape = tuple(spec[1])
            if shape and shape[0] in buckets and shape[1:] in trailing:
                found.add(shape[0])
    return sorted(found)


class Replica:
    """One worker thread + one Predictor (and its bucket rebinds)."""

    def __init__(self, index, ctx, predictor, buckets, batcher, stats=None):
        self.index = index
        self.ctx = ctx
        self.buckets = sorted(buckets)
        self._batcher = batcher
        self._stats = stats
        self._preds = {self.buckets[-1]: predictor}
        self._base = predictor
        self._thread = None
        self._inflight = 0
        # serializes forwards against hot-reload weight swaps so a
        # micro-batch never runs on a half-swapped parameter set
        self._swap_lock = threading.Lock()
        self.batches_served = 0
        self.requests_served = 0

    # ------------------------------------------------------------------
    def _pred_for(self, bucket):
        """Bucket-shaped Predictor, rebound lazily; parameters are shared
        device arrays (Predictor.reshape), so this costs one bind + (on
        first forward) one XLA compile per bucket, ever.  The rebind
        map is shared between the worker loop and external callers
        (warmup on a live replica), so get-or-bind holds the swap lock
        — a racy double-rebind would waste a bind and drop one of the
        two Predictors mid-bookkeeping (mx.analyze threads pass)."""
        with self._swap_lock:
            pred = self._preds.get(bucket)
            if pred is None:
                shapes = {name: (bucket,) + tuple(shape[1:])
                          for name, shape
                          in self._base.input_shapes.items()}
                pred = self._base.reshape(shapes)
                self._preds[bucket] = pred
        return pred

    def warm_bucket(self, bucket):
        """Bind + compile ONE bucket shape (one warmup-pool job)."""
        pred = self._pred_for(bucket)
        dummy = {name: _np.zeros((bucket,) + tuple(shape[1:]),
                                 dtype=_np.float32)
                 for name, shape in self._base.input_shapes.items()}
        pred.forward(**dummy)

    def warmup(self, buckets=None):
        """Compile every bucket shape before serving (cold-start cost paid
        up front, not by the first unlucky requests); buckets compile
        concurrently (MXNET_AOT_WARMUP_THREADS).  ``buckets`` restricts
        the ladder — the manifest-driven path (mx.aot) warms only the
        shapes a previous process actually served."""
        if buckets is None:
            picked = self.buckets
        else:
            allowed = set(buckets)
            picked = [b for b in self.buckets if b in allowed]
        return _run_warmup([(self, b) for b in picked])

    # ------------------------------------------------------------------
    @property
    def inflight(self):
        return self._inflight

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="mx-serving-replica-%d" % self.index,
            daemon=True)
        self._thread.start()

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def _run(self):
        while True:
            batch = self._batcher.next_batch()
            if batch is None:       # queue closed and drained
                return
            self._inflight = batch.n_real
            try:
                self._execute(batch)
            finally:
                self._inflight = 0

    def swap_params(self, arg_params, aux_params=None):
        """Hot-swap this replica's weights in place. All bucket rebinds
        share the base Predictor's device-resident NDArrays
        (``Executor.reshape``), so one ``_set_data`` per tensor updates
        every compiled shape with ZERO recompiles; the swap lock only
        excludes the replica's own forward, so queued requests keep
        flowing and none are dropped."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        exe = self._base._exe
        with self._swap_lock:
            for params, live in ((arg_params, exe.arg_dict),
                                 (aux_params or {}, exe.aux_dict)):
                for name, v in params.items():
                    dst = live.get(name)
                    if dst is None or name in self._base._input_shapes:
                        continue
                    data = v._data if hasattr(v, "_data") \
                        else jnp.asarray(np.asarray(v))  # analyze: ok(hostsync) hot-reload weight staging from host checkpoint values; serialized by the swap lock, not on the forward path
                    if data.dtype != dst._data.dtype:
                        data = data.astype(dst._data.dtype)
                    dst._set_data(jax.device_put(
                        data, self.ctx.jax_device))

    def _execute(self, mb):
        stats = self._stats
        # batch-level forward span, parented under the OLDEST member
        # request's span (requests are in arrival order) so each trace
        # renders admission -> forward; member rids ride as attrs
        from .. import telemetry as _tm
        lead = next((r.span for r in mb.requests if r.span is not None),
                    None)
        fwd_span = _tm.tracing.start_span(
            "serving.forward", parent=getattr(lead, "context", None),
            replica=self.index, bucket=mb.bucket, n_real=mb.n_real,
            rids=[r.rid for r in mb.requests]) if lead is not None \
            else _tm.tracing.NULL_SPAN
        try:
            pred = self._pred_for(mb.bucket)
            with self._swap_lock:
                outs = pred.forward(**mb.arrays)
        except Exception as exc:     # deliver, don't kill the worker
            fwd_span.end(error=type(exc).__name__)
            for req in mb.requests:
                settle_exception(req.future, exc)
            if stats is not None:
                stats.record_failed_batch(self.index, mb, exc)
            return
        fwd_span.end()
        # slice the padding off before delivery — rows [n_real:] are
        # replicas of row 0 and must never leak into any result
        for i, req in enumerate(mb.requests):
            if req.future.cancelled():
                if stats is not None:
                    stats.record_cancelled(req)
                continue
            if req.expired():
                landed = settle_exception(req.future, DeadlineExceededError(
                    "request %d deadline expired during forward" % req.rid))
                if stats is not None:
                    (stats.record_expired if landed
                     else stats.record_cancelled)(req)
                continue
            try:
                req.future.set_result([out[i] for out in outs])
            except Exception:        # client cancelled in the window above
                if stats is not None:
                    stats.record_cancelled(req)
        self.batches_served += 1
        self.requests_served += mb.n_real
        if stats is not None:
            stats.record_batch(self.index, mb)

    def snapshot(self):
        return {"replica": self.index, "ctx": str(self.ctx),
                "inflight": self._inflight,
                "batches_served": self.batches_served,
                "requests_served": self.requests_served,
                "buckets_bound": sorted(self._preds)}


class ReplicaPool:
    """N replicas pulling from one shared batcher."""

    def __init__(self, contexts, make_predictor, buckets, batcher,
                 stats=None, warmup=True):
        self._make_predictor = make_predictor
        self._buckets = sorted(buckets)
        self._batcher = batcher
        self._stats = stats
        self.replicas = []
        for i, ctx in enumerate(contexts):
            pred = make_predictor(ctx)
            self.replicas.append(
                Replica(i, ctx, pred, buckets, batcher, stats))
        if warmup:
            self.warmup()

    def warmup(self, manifest=None, replicas=None):
        """Warm every (replica, bucket) pair through ONE thread pool
        (the pool width spans replicas too, not just buckets).  With an
        AOT manifest, only manifest-compiled buckets warm; a manifest
        for a different model matches nothing and the full ladder warms
        instead.  Returns the number of programs dispatched."""
        reps = self.replicas if replicas is None else replicas
        jobs = []
        for rep in reps:
            picked = rep.buckets
            if manifest is not None:
                sel = manifest_buckets(manifest.get("entries", []),
                                       rep._base.input_shapes,
                                       rep.buckets)
                if sel:
                    picked = sel
            jobs += [(rep, b) for b in picked]
        return _run_warmup(jobs)

    def add_replica(self, ctx, warmup=True, manifest=None, start=True):
        """Scale up: bind a new replica and (by default) warm its whole
        bucket ladder BEFORE it starts pulling from the batcher, so a
        scale-up never routes traffic onto a compiling replica."""
        rep = Replica(len(self.replicas), ctx, self._make_predictor(ctx),
                      self._buckets, self._batcher, self._stats)
        if warmup:
            self.warmup(manifest=manifest, replicas=[rep])
        self.replicas.append(rep)
        if start:
            rep.start()
        return rep

    def start(self):
        for rep in self.replicas:
            rep.start()

    def join(self, timeout=None):
        for rep in self.replicas:
            rep.join(timeout)

    def snapshot(self):
        return [rep.snapshot() for rep in self.replicas]
