"""mx.rtc — runtime kernel compilation.

Reference parity: python/mxnet/rtc.py (``CudaModule``: NVRTC-compile
CUDA source at runtime, ``get_kernel(name, signature)``, ``launch``
over grid/block dims; src/common/rtc.cc). The TPU has no user-facing
runtime C compilation — custom kernels are **Pallas** Python functions
compiled by XLA — so the module shape is preserved with Pallas as the
kernel language:

    mod = mx.rtc.PallasModule(axpy=my_axpy_kernel)
    k = mod.get_kernel("axpy", out_shape=(n,), out_dtype="float32",
                       grid=(blocks,))
    y = k.launch([a, x], mx.tpu(0))

A kernel body takes ``(*input_refs, out_ref)`` pallas Refs. On
non-TPU backends kernels run in pallas interpret mode, so the same code
tests on CPU. ``CudaModule`` raises with guidance — CUDA source cannot
target a TPU.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .context import current_context
from .ndarray.ndarray import NDArray

__all__ = ["CudaModule", "PallasModule"]


def CudaModule(*args, **kwargs):
    raise MXNetError(
        "mx.rtc.CudaModule compiles CUDA source, which cannot target a "
        "TPU. Write the kernel as a Pallas function and wrap it in "
        "mx.rtc.PallasModule (kernel model: "
        "https://docs.jax.dev/en/latest/pallas/index.html).")


class PallasKernel:
    """A launchable Pallas kernel (the CudaKernel analog)."""

    def __init__(self, name, body, out_shape, out_dtype, grid, in_specs,
                 out_specs, interpret):
        self._name = name
        self._body = body
        self._out_shape = tuple(out_shape)
        self._out_dtype = out_dtype
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._interpret = interpret
        self._compiled = None

    def _fn(self):
        if self._compiled is None:
            from jax.experimental import pallas as pl
            import jax.numpy as jnp

            interpret = self._interpret
            if interpret is None:
                interpret = jax.default_backend() != "tpu"
            kwargs = {}
            if self._grid is not None:
                kwargs["grid"] = self._grid
            if self._in_specs is not None:
                kwargs["in_specs"] = self._in_specs
            if self._out_specs is not None:
                kwargs["out_specs"] = self._out_specs
            # analyze: ok(retrace) user-authored RTC kernel — built once per CudaKernel and counted by the jit site below
            call = pl.pallas_call(
                self._body,
                out_shape=jax.ShapeDtypeStruct(self._out_shape,
                                               jnp.dtype(self._out_dtype)),
                interpret=interpret, **kwargs)
            # analyze: ok(retrace) user-authored RTC kernel compiles once per CudaKernel construction (the reference's nvrtc contract)
            self._compiled = jax.jit(call)
        return self._compiled

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run the kernel on NDArray/array inputs; returns an NDArray.
        ``grid_dims``/``block_dims``/``shared_mem`` are accepted for
        CudaKernel.launch signature parity — the Pallas grid is fixed at
        ``get_kernel`` time (blocks/threads are the compiler's job on
        TPU)."""
        ctx = ctx if ctx is not None else current_context()
        vals = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._fn()(*vals)
        return NDArray(out, ctx)

    def __call__(self, *args):
        return self.launch(list(args))


class PallasModule:
    """Named collection of Pallas kernels (the CudaModule analog)."""

    def __init__(self, **kernels):
        if not kernels:
            raise MXNetError("PallasModule needs at least one "
                             "name=kernel_fn pair")
        self._kernels = dict(kernels)

    def get_kernel(self, name, out_shape, out_dtype="float32", grid=None,
                   in_specs=None, out_specs=None, interpret=None):
        """Bind a kernel body to output shape/dtype (+ optional pallas
        grid/BlockSpecs); mirrors CudaModule.get_kernel(name, signature)."""
        if name not in self._kernels:
            raise MXNetError("no kernel '%s' in module (have %s)"
                             % (name, sorted(self._kernels)))
        return PallasKernel(name, self._kernels[name], out_shape,
                            out_dtype, grid, in_specs, out_specs,
                            interpret)
