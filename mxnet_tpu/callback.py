"""Training callbacks.

Behavioral parity with the reference's ``python/mxnet/callback.py`` (same
constructor signatures, same log-line shapes so ``parse_log.py`` works), but
re-derived: throughput is computed by a small monotonic-clock ``_RateMeter``
instead of inline tic/count bookkeeping, and log formatting is centralised.
Batch callbacks receive the ``BatchEndParam`` namedtuple emitted by
``module.base_module``; epoch callbacks receive ``(epoch, symbol, arg, aux)``.
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint", "module_checkpoint",
           "log_train_metric", "LogValidationMetricsCallback"]


class _RateMeter:
    """Samples/sec over a sliding window of batch-end events.

    ``tick(count)`` returns a rate once ``frequent`` batches have elapsed
    since the last emission, else None.  Detects epoch restarts (count going
    backwards) and re-arms.
    """

    def __init__(self, unit_per_tick: int, frequent: int):
        self.unit = unit_per_tick
        self.frequent = frequent
        self._mark: float | None = None
        self._mark_count = 0

    def tick(self, count: int) -> float | None:
        now = time.monotonic()
        if self._mark is None or count < self._mark_count:
            self._mark, self._mark_count = now, count
            return None
        if count - self._mark_count < self.frequent or count % self.frequent:
            return None
        elapsed = max(now - self._mark, 1e-9)
        rate = (count - self._mark_count) * self.unit / elapsed
        self._mark, self._mark_count = now, count
        return rate


def _metric_pairs(metric) -> list[tuple[str, float]]:
    return [] if metric is None else list(metric.get_name_value())


class Speedometer:
    """Log throughput (and current train metrics) every ``frequent`` batches.

    Log-line format matches the reference so log-parsing tools keep working:
    ``Epoch[e] Batch [n]\\tSpeed: r samples/sec\\tname=value...``

    **Sync points** (docs/TRAINING.md): with the fused fit step active,
    train metrics live in a device-resident accumulator and the fit loop
    never blocks — this callback is the ONLY mid-epoch reader. Metric
    values are read exclusively at the ``frequent`` gate (the early
    return below), so the per-batch invocations between emissions touch
    nothing device-resident and force no host sync; each emission costs
    exactly one accumulator snapshot readback (plus a second device
    round-trip for ``reset`` when ``auto_reset`` seeds fresh scalars).
    The remaining scheduled syncs in ``fit`` are the epoch-end metric
    log and the optional ``MXNET_FIT_SYNC_EVERY`` depth bound."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._meter = _RateMeter(batch_size, frequent)

    def __call__(self, param):
        rate = self._meter.tick(param.nbatch)
        if rate is None:
            # between emissions: no metric access, no device readback
            return
        pairs = _metric_pairs(param.eval_metric)
        if pairs:
            if self.auto_reset:
                param.eval_metric.reset()
            tail = "".join(f"\t{n}={v:f}" for n, v in pairs)
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, param.nbatch, rate, tail)
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, param.nbatch, rate)


class ProgressBar:
    """Render ``[===---] pct%`` for the current epoch at each batch end."""

    def __init__(self, total, length=80):
        self.total = total
        self.bar_len = length

    def __call__(self, param):
        frac = min(max(param.nbatch / float(self.total), 0.0), 1.0)
        n_fill = round(self.bar_len * frac)
        bar = "=" * n_fill + "-" * (self.bar_len - n_fill)
        logging.info("[%s] %s%s\r", bar, -(-int(frac * 1000) // 10), "%")


def do_checkpoint(prefix, period=1, async_write=False):
    """Epoch-end callback saving ``prefix-symbol.json`` + ``prefix-NNNN.params``
    every ``period`` epochs via :func:`mxnet_tpu.model.save_checkpoint`.

    ``async_write=True`` routes the save through mx.checkpoint's
    background writer (docs/CHECKPOINT.md): the callback snapshots the
    (already host-resident) params and returns immediately; the writer
    commits the SAME epoch-numbered ``prefix-NNNN.params`` file
    crash-safely (tmp + fsync + atomic rename) plus a checksum
    manifest. Default stays the legacy blocking in-place write."""
    stride = max(int(period), 1)
    writer = None

    def _on_epoch_end(epoch, sym, arg, aux):
        nonlocal writer
        done = epoch + 1
        if done % stride == 0:
            if not async_write:
                from .model import save_checkpoint
                save_checkpoint(prefix, done, sym, arg, aux)
                return
            from . import checkpoint as _ckpt
            if writer is None:
                writer = _ckpt.AsyncCheckpointWriter()
            state = _ckpt.capture_params(arg, aux, symbol=sym, epoch=done)
            writer.submit(state, prefix, done)

    def _drain(timeout=None):
        """Wait for queued async saves (call after fit() returns before
        reading the files; a no-op in legacy blocking mode)."""
        return True if writer is None else writer.drain(timeout)

    def _close(timeout=None):
        """Drain and stop the writer thread (long-lived processes that
        build many callbacks should close each when done with it)."""
        return True if writer is None else writer.close(timeout)

    _on_epoch_end.drain = _drain
    _on_epoch_end.close = _close
    return _on_epoch_end


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False,
                      async_write=False):
    """Epoch-end callback delegating to ``mod.save_checkpoint`` (optionally
    with optimizer state) every ``period`` epochs.

    ``async_write=True`` upgrades the save to a FULL mx.checkpoint
    snapshot committed on the background writer — params, optimizer
    state (when ``save_optimizer_states``), error-feedback residuals,
    RNG and lr position — while keeping the epoch-numbered
    ``prefix-NNNN.params``/``.states`` filename contract, so
    ``Module.load(prefix, epoch)`` keeps working on the result."""
    stride = max(int(period), 1)
    manager = None

    def _on_epoch_end(epoch, sym=None, arg=None, aux=None):
        nonlocal manager
        done = epoch + 1
        if done % stride == 0:
            if not async_write:
                mod.save_checkpoint(prefix, done, save_optimizer_states)
                return
            if manager is None:
                from .checkpoint import CheckpointManager
                manager = CheckpointManager(
                    prefix, module=mod, keep=0,
                    save_optimizer=save_optimizer_states,
                    install_preemption=False)
            manager.save(epoch=done, tag=done)

    def _drain(timeout=None):
        """Wait for queued async saves (call after fit() returns before
        reading the files; a no-op in legacy blocking mode)."""
        return True if manager is None else manager.drain(timeout)

    def _close(timeout=None):
        """Drain and stop the manager's writer thread (long-lived
        processes that build many callbacks should close each)."""
        if manager is not None:
            manager.close(timeout)
        return True

    _on_epoch_end.drain = _drain
    _on_epoch_end.close = _close
    return _on_epoch_end


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging current train metrics every ``period``
    batches (``Iter[e] Batch[n] Train-name=value``)."""

    def _on_batch_end(param):
        if param.nbatch % period:
            return
        for name, value in _metric_pairs(param.eval_metric):
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset and param.eval_metric is not None:
            param.eval_metric.reset()
    return _on_batch_end


class LogValidationMetricsCallback:
    """Epoch-end eval callback: ``Epoch[e] Validation-name=value`` lines."""

    def __call__(self, param):
        for name, value in _metric_pairs(param.eval_metric):
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
