"""Data iterators.

Reference parity: python/mxnet/io.py (DataIter protocol :182, NDArrayIter
:546, PrefetchingIter :349, MXDataIter :766) and src/io/ C++ iterators
(MNISTIter, CSVIter, ImageRecordIter). All iterators yield ``DataBatch``
with ``data``/``label`` NDArray lists and ``pad`` for final-batch handling.
"""
from __future__ import annotations

import os
import queue
import struct
import threading

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array
from ..ndarray.ndarray import concatenate

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MXDataIter", "CSVIter", "LibSVMIter",
           "MNISTIter", "ImageRecordIter"]


class DataDesc:
    """Name+shape(+dtype+layout) of one input (reference io.py DataDesc)."""

    def __init__(self, name, shape, dtype=_np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    def __iter__(self):  # tuple-compat: name, shape
        yield self.name
        yield self.shape

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __len__(self):
        return 2

    def __eq__(self, other):
        if isinstance(other, DataDesc):
            return self.name == other.name and self.shape == other.shape
        if isinstance(other, tuple):
            return tuple(self) == other
        return NotImplemented

    def __hash__(self):
        return hash((self.name, self.shape))

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict.get(x[0], _np.float32))
                    for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        return "DataBatch: data shapes %s label shapes %s" % (
            [d.shape for d in self.data] if self.data else None,
            [l.shape for l in self.label] if self.label else None)


class DataIter:
    """Iterator protocol (reference io.py:182)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise ValueError("data cannot be None")
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = nd_array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference io.py:546): shuffle, pad/discard/
    roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = _np.arange(self.num_data)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self.num_pad = 0
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self._cache_np = {k: v.asnumpy() for k, v in self.data + self.label}
        if shuffle:
            self._shuffle_data()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def _shuffle_data(self):
        _np.random.shuffle(self.idx)

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            # leftover of the wrapped last batch starts the next epoch
            # (reference io.py:700 reset)
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, kv_list):
        out = []
        for k, _ in kv_list:
            src = self._cache_np[k]
            start = self.cursor
            end = self.cursor + self.batch_size
            if end <= self.num_data:
                part = src[self.idx[start:end]]
                self.num_pad = 0
            else:
                # wrap modulo num_data so the batch is always full even
                # when batch_size exceeds the dataset size
                sel = self.idx[_np.arange(start, end) % self.num_data]
                part = src[sel]
                self.num_pad = end - self.num_data
            out.append(nd_array(part))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py:288)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


from .. import telemetry as _telemetry

# prefetch-pipeline health: occupancy sampled at every consumer get()
# (how many decoded batches sat ready), plus a served-batch counter
_PREFETCH_OCC = _telemetry.REGISTRY.gauge(
    "io_prefetch_occupancy",
    "decoded batches waiting in the PrefetchingIter queue at get() time",
    unit="batches")
_PREFETCH_BATCHES = _telemetry.REGISTRY.counter(
    "io_prefetch_batches", "batches served through PrefetchingIter")
_DATA_WAIT_MS = _telemetry.REGISTRY.histogram(
    "io_data_wait_ms",
    "time the consumer blocked waiting for a prefetched batch — the "
    "per-step data-wait the fit loop's io.data_wait trace span renders",
    unit="ms")


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference io.py:349 + C++
    iter_prefetcher.h): overlaps host-side batch prep with device compute.

    With ``ctx`` set to an accelerator context, the worker ALSO starts
    the host->device transfer (``jax.device_put``) for each prefetched
    batch, double-buffered by ``prefetch_depth``: while the device runs
    step N, batch N+1 is already decoding AND transferring — the
    TPU-native analog of the reference's pinned-memory staging in
    iter_prefetcher.h (transfers are async in jax; dispatching them from
    the worker overlaps them with compute).

    With ``ctx`` a LIST of contexts, the worker shards each batch along
    its leading axis over a ``dp`` mesh of those devices at prefetch
    time, so a multi-device training step (executor_group /
    module/fused_fit.py) receives device-resident shards instead of
    splitting the batch on the fit thread. A batch whose leading dim
    does not divide the device count falls back to the first device."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, ctx=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._depth = prefetch_depth
        self._mesh = None
        if isinstance(ctx, (list, tuple)):
            ctx = list(ctx)
            if len(ctx) > 1:
                import numpy as _np
                from jax.sharding import Mesh
                self._mesh = Mesh(_np.array([c.jax_device for c in ctx]),
                                  ("dp",))
            ctx = ctx[0] if ctx else None
        self._ctx = ctx
        self._queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    def _to_device(self, batches):
        if self._ctx is None:
            return batches
        import jax
        from ..ndarray.ndarray import NDArray
        dev = self._ctx.jax_device
        mesh = self._mesh

        def place_dev0(nd):
            return NDArray(jax.device_put(nd._data, dev), self._ctx)

        def _batch_place(b):
            """Sharding is decided per BATCH, not per array: either every
            array (data and label) shards over the mesh or the whole
            batch stays on device 0 — a mixed batch would hand the
            consuming jitted step a new input-sharding combination
            (extra compile + resharding transfers)."""
            if mesh is None:
                return place_dev0
            ndev = mesh.devices.size
            arrays = list(b.data) + list(b.label or [])
            if not all(a.shape and a.shape[0] % ndev == 0 for a in arrays):
                return place_dev0
            from jax.sharding import NamedSharding, PartitionSpec as P
            bsh = NamedSharding(mesh, P("dp"))
            return lambda nd: NDArray(jax.device_put(nd._data, bsh),
                                      self._ctx)

        out = []
        for b in batches:
            place = _batch_place(b)
            out.append(DataBatch([place(d) for d in b.data],
                                 ([place(l) for l in b.label]
                                  if b.label is not None else None),
                                 b.pad, b.index,
                                 bucket_key=getattr(b, "bucket_key", None),
                                 provide_data=getattr(b, "provide_data", None),
                                 provide_label=getattr(b, "provide_label",
                                                       None)))
        return out

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _start(self):
        def worker():
            while not self._stop.is_set():
                try:
                    batches = [i.next() for i in self.iters]
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(self._to_device(batches))
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for i in self.iters:
            i.reset()
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self._depth)
        self._start()

    def next(self):
        import time as _time
        sp = _telemetry.tracing.start_span("io.data_wait")
        t0 = _time.perf_counter()
        batches = self._queue.get()
        wait_ms = (_time.perf_counter() - t0) * 1e3
        _DATA_WAIT_MS.observe(wait_ms)
        sp.end(occupancy=self._queue.qsize())
        # occupancy AFTER the get: batches still staged for future steps
        # — 0 here while the device is busy means the input pipeline is
        # the bottleneck (docs/OBSERVABILITY.md)
        _PREFETCH_OCC.set(self._queue.qsize())
        if batches is None:
            raise StopIteration
        _PREFETCH_BATCHES.inc()
        batch = batches[0]
        if len(batches) > 1:
            data = sum([b.data for b in batches], [])
            label = sum([b.label for b in batches], [])
            return DataBatch(data, label, batch.pad, batch.index)
        return batch

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False


class CSVIter(DataIter):
    """CSV file iterator (reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        else:
            label = _np.zeros((data.shape[0],), dtype=dtype)
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="pad" if round_batch else "discard",
                                  data_name="data", label_name="label")

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def next(self):
        return self._inner.next()

    def reset(self):
        self._inner.reset()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """Zero-based-indexed LibSVM text file → CSR data batches
    (reference src/io/iter_libsvm.cc). Labels are the leading scalar of
    each data line unless ``label_libsvm`` names a separate LibSVM file
    (multi-dimensional labels, returned dense). ``num_parts``/
    ``part_index`` partition rows round-robin for distributed reading
    (the reference partitions the byte stream via dmlc InputSplit)."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=(1,), num_parts=1,
                 part_index=0, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        if num_parts <= 0 or not 0 <= part_index < num_parts:
            raise ValueError("invalid num_parts=%s part_index=%s"
                             % (num_parts, part_index))
        self._dtype = dtype
        self._dim = int(data_shape[0]) if isinstance(
            data_shape, (tuple, list)) else int(data_shape)
        labels, rows = self._parse(data_libsvm)
        if label_libsvm is not None:
            ldim = int(label_shape[0]) if isinstance(
                label_shape, (tuple, list)) else int(label_shape)
            lab_vals, lab_rows = self._parse(label_libsvm)
            labels = [self._densify(r, ldim) for r in lab_rows]
        else:
            labels = [[l] for l in labels]
        labels = _np.asarray(labels, dtype=dtype)
        if labels.shape[-1] == 1:
            labels = labels.reshape(labels.shape[:-1])
        self._rows = rows[part_index::num_parts]
        self._labels = labels[part_index::num_parts]
        self._round_batch = round_batch
        self._cursor = 0
        self._provide_data = [DataDesc("data", (batch_size, self._dim),
                                       dtype)]
        self._provide_label = [DataDesc(
            "label", (batch_size,) + tuple(labels.shape[1:]), dtype)]

    @staticmethod
    def _parse(path):
        labels, rows = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                if ":" in parts[0]:
                    label, feats = 0.0, parts
                else:
                    label, feats = float(parts[0]), parts[1:]
                row = []
                for tok in feats:
                    idx, val = tok.split(":")
                    row.append((int(idx), float(val)))
                labels.append(label)
                rows.append(row)
        return labels, rows

    def _densify(self, row, dim):
        out = [0.0] * dim
        for idx, val in row:
            out[idx] = val
        return out

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self._cursor = 0

    def next(self):
        n = len(self._rows)
        if self._cursor >= n:
            raise StopIteration
        take = list(range(self._cursor,
                          min(self._cursor + self.batch_size, n)))
        pad = self.batch_size - len(take)
        if pad:
            if self._round_batch:
                # wrap to the beginning, repeatedly if batch_size
                # exceeds the partition size
                take += [j % n for j in range(pad)]
            else:
                self._cursor = n
                raise StopIteration
        self._cursor += self.batch_size

        # assemble one CSR batch
        from ..ndarray import sparse as _sp
        indptr, indices, values = [0], [], []
        for i in take:
            for idx, val in self._rows[i]:
                indices.append(idx)
                values.append(val)
            indptr.append(len(indices))
        data = _sp.csr_matrix(
            (_np.asarray(values, dtype=self._dtype),
             _np.asarray(indices, dtype=_np.int64),
             _np.asarray(indptr, dtype=_np.int64)),
            shape=(self.batch_size, self._dim))
        label = nd_array(self._labels[_np.asarray(take)], dtype=self._dtype)
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self._provide_data,
                         provide_label=self._provide_label)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference src/io/iter_mnist.cc)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=None, input_shape=None, **kwargs):
        super().__init__(batch_size)
        imgs = self._read_idx(image)
        labels = self._read_idx(label)
        imgs = imgs.astype("float32") / 255.0
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1], imgs.shape[2])
        if input_shape is not None:
            imgs = imgs.reshape((imgs.shape[0],) + tuple(input_shape))
        self._inner = NDArrayIter(imgs, labels.astype("float32"), batch_size,
                                  shuffle=shuffle, last_batch_handle="discard")

    @staticmethod
    def _read_idx(path):
        if not os.path.exists(path):
            raise MXNetError("MNIST file not found: %s" % path)
        import gzip
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            data = _np.frombuffer(f.read(), dtype=_np.uint8)
            return data.reshape(dims)

    def next(self):
        return self._inner.next()

    def reset(self):
        self._inner.reset()

    def iter_next(self):
        return self._inner.iter_next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


def MXDataIter(handle, **kwargs):  # pragma: no cover - compat shim
    raise MXNetError("MXDataIter wraps C++ iterators in the reference; use "
                     "the Python-native iterators (NDArrayIter, "
                     "ImageRecordIter, CSVIter, MNISTIter) instead")


def ImageRecordIter(**kwargs):
    """RecordIO image iterator — implemented in image/record_iter.py over the
    native recordio reader (reference src/io/iter_image_recordio_2.cc)."""
    from ..image.record_iter import ImageRecordIter as _Impl
    return _Impl(**kwargs)
