"""Data IO (reference parity: python/mxnet/io.py + src/io/).

The reference's C++ iterator chain (record reader → OMP decode → augment →
batch → prefetch, src/io/iter_image_recordio_2.cc) maps to Python iterators
with a background-thread prefetcher; device transfer is asynchronous via JAX
so the `PrefetcherIter` role (overlap host decode with device compute) is
preserved.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MXDataIter, CSVIter, LibSVMIter,
                 MNISTIter, ImageRecordIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MXDataIter", "CSVIter", "LibSVMIter",
           "MNISTIter", "ImageRecordIter"]
