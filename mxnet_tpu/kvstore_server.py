"""kvstore_server — parameter-server bootstrap.

Reference parity: python/mxnet/kvstore_server.py enters the ps-lite
server loop when a process is launched with DMLC_ROLE=server. Here that
role is REAL for ``dist_async``: the process runs the threaded TCP
parameter server from kvstore_async.py (immediate Hogwild-style applies,
optimizer-on-server). ``dist_sync`` still needs no servers — it rides
jax.distributed collectives with replicated state (kvstore_dist.py) — so
tools/launch.py spawns servers only when ``-s`` is given.

Server i of S listens on DMLC_PS_ROOT_PORT + i (workers shard keys
across servers by stable hash, kvstore_async.py _server_of).
"""
from __future__ import annotations

import logging
import os

__all__ = ["_init_kvstore_server_module"]


def _init_kvstore_server_module():
    """Called from mxnet_tpu/__init__.py AFTER the package is fully
    imported (serving mid-import would deadlock handler threads on the
    import lock)."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role == "scheduler":
        logging.warning(
            "DMLC_ROLE=scheduler: the TPU-native kvstore has no scheduler "
            "process (jax.distributed / the launcher own the topology). "
            "Exiting idle.")
        raise SystemExit(0)
    if role == "server":
        # SECURITY: the wire protocol is pickle (like the reference's
        # ps-lite, it assumes a trusted cluster network) — bind
        # localhost unless the launcher explicitly widens it
        # (launch_ssh sets DMLC_PS_BIND=0.0.0.0 for cross-host jobs)
        host = os.environ.get("DMLC_PS_BIND", "127.0.0.1")
        port = (int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
                + int(os.environ.get("MXTPU_SERVER_RANK", "0")))
        nworkers = int(os.environ.get("DMLC_NUM_WORKER", "1"))

        def _serve_when_ready():
            # Serving must not start while ``import mxnet_tpu`` is still
            # in progress: the importing main thread holds the package
            # import lock, and anything a handler does that resolves
            # ``mxnet_tpu.*`` (even pickle.loads of an optimizer) would
            # deadlock in _lock_unlock_module. Wait for the package spec
            # to finish initializing, then serve. The thread is
            # NON-daemon: it keeps the server process alive after the
            # ``python -c 'import mxnet_tpu'`` main thread exits
            # (reference: ps-lite RunServer blocks the process).
            import sys
            import time
            while True:
                spec = getattr(sys.modules.get("mxnet_tpu"), "__spec__",
                               None)
                if spec is None or not getattr(spec, "_initializing",
                                               False):
                    break
                time.sleep(0.01)
            from .kvstore_async import serve_forever
            logging.info("parameter server listening on %s:%d (%d workers)",
                         host, port, nworkers)
            serve_forever(host, port, nworkers)

        import threading
        threading.Thread(target=_serve_when_ready, daemon=False,
                         name="mxtpu-kvstore-server").start()


if __name__ == "__main__":
    # ``python -m mxnet_tpu.kvstore_server``: if DMLC_ROLE=server was
    # already set, the package import above has started the serve thread
    # — starting a second one would fight over the port.
    if os.environ.get("DMLC_ROLE") != "server":
        os.environ["DMLC_ROLE"] = "server"
        _init_kvstore_server_module()
