"""kvstore_server — parameter-server bootstrap (reference parity shim).

Reference: python/mxnet/kvstore_server.py enters the ps-lite server loop
when a process is launched with DMLC_ROLE=server. The TPU-native
distributed kvstore has **no server processes** — ps-lite is replaced by
jax.distributed collectives with the server state replicated on every
worker (kvstore_dist.py) — so a process launched in the server role has
nothing to do and this module documents exactly that. tools/launch.py
accordingly spawns workers only.
"""
from __future__ import annotations

import logging
import os

__all__ = ["_init_kvstore_server_module"]


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        logging.warning(
            "process launched with DMLC_ROLE=%s: the TPU-native kvstore "
            "has no %s processes (collectives replace ps-lite; see "
            "kvstore_dist.py). Exiting idle.", role, role)
        raise SystemExit(0)


if os.environ.get("DMLC_ROLE") in ("server", "scheduler"):
    _init_kvstore_server_module()
