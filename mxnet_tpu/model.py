"""Model helpers: checkpointing + kvstore-driven parameter updates.

Reference parity: python/mxnet/model.py (_create_kvstore :54,
_initialize_kvstore :116, _update_params_on_kvstore :145, save_checkpoint
:384, load_checkpoint :414). Checkpoint format keeps the reference's layout:
``prefix-symbol.json`` + ``prefix-%04d.params`` with ``arg:``/``aux:``
prefixed keys.
"""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(reference model.py:54) Returns (kv, update_on_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(arg_params[p].size for p in arg_params) \
                    if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str, or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """(reference model.py:116)"""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """(reference model.py:145) push grad, pull back updated weight."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """(reference model.py:163) update on workers via the local updater."""
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            updater(*upd)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """(reference model.py:384) prefix-symbol.json + prefix-%04d.params"""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """(reference model.py:414) returns (symbol, arg_params, aux_params)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


def load_params(prefix, epoch):
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params
