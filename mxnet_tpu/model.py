"""Model helpers: checkpointing + kvstore-driven parameter updates.

Reference parity: python/mxnet/model.py (_create_kvstore :54,
_initialize_kvstore :116, _update_params_on_kvstore :145, save_checkpoint
:384, load_checkpoint :414). Checkpoint format keeps the reference's layout:
``prefix-symbol.json`` + ``prefix-%04d.params`` with ``arg:``/``aux:``
prefixed keys.
"""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(reference model.py:54) Returns (kv, update_on_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore \
                and not kvstore.startswith("tpu") and kvstore != "nccl":
            # 'tpu' (and its 'nccl' alias) stays a real store even on
            # one local device: the world may span processes, and the
            # single-process path must exercise the same code the pod
            # runs (kvstore_tpu/)
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(arg_params[p].size for p in arg_params) \
                    if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str, or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """(reference model.py:116)"""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _batched_push(kvstore, param_names, grad_arrays, push_order):
    """ONE push call covering every key with a gradient — the bucketed
    kvstore hot path (kvstore_fused.py) streams size-capped compiled
    buckets instead of dispatching per-key. ``push_order``
    (executor_group.push_order) lists indices in backward
    gradient-availability order; with the engine's streaming flush, the
    buckets those keys fill dispatch while this loop is still walking
    the remaining keys. Returns (names, grads) pushed, in push order."""
    order = list(push_order) if push_order is not None \
        else list(range(len(grad_arrays)))
    names, grads, prios = [], [], []
    for index in order:
        if grad_arrays[index][0] is None:
            continue
        names.append(param_names[index])
        grads.append(grad_arrays[index])
        prios.append(-index)
    if names:
        kvstore.push(names, grads, priority=prios)
    return names, grads


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names,
                              push_order=None):
    """(reference model.py:145) push grad, pull back updated weight;
    push batched (see _batched_push), pull batched in forward order —
    matching next-forward consumption."""
    names, _ = _batched_push(kvstore, param_names, grad_arrays, push_order)
    if not names:
        return
    pull_names, pull_args = [], []
    for index in range(len(param_arrays)):
        if grad_arrays[index][0] is None:
            continue
        pull_names.append(param_names[index])
        pull_args.append(param_arrays[index])
    kvstore.pull(pull_names, out=pull_args)


def _local_updater_key(index, num_device=1, device=0):
    """Updater state key for worker-side updates (reference model.py:163
    interleaves per-device: ``i * num_device + k``). Shared by
    :func:`_update_params` and the fused fit step
    (module/fused_fit.py) so optimizer state saved by one path loads
    into the other."""
    return index * num_device + device


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None, push_order=None):
    """(reference model.py:163) update on workers via the local updater;
    the kvstore reduce runs batched (see _batched_push)."""
    if kvstore:
        names, grads = _batched_push(kvstore, param_names, grad_arrays,
                                     push_order)
        if names:
            kvstore.pull(names, out=grads)
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((_local_updater_key(i, num_device, k), g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            updater(*upd)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """(reference model.py:384) prefix-symbol.json + prefix-%04d.params"""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """(reference model.py:414) returns (symbol, arg_params, aux_params)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


def load_params(prefix, epoch):
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


class FeedForward:
    """Legacy estimator API (reference model.py:452 FeedForward — already
    deprecated there in favor of Module; kept as a thin Module adapter so
    old scripts run)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter
        if isinstance(X, DataIter) or hasattr(X, "provide_data"):
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                           shuffle=shuffle)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        """(reference model.py FeedForward.fit)"""
        from .module import Module
        train = self._as_iter(X, y, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._as_iter(*eval_data) \
                if isinstance(eval_data, tuple) else self._as_iter(eval_data)
        self._module = Module(self.symbol, context=self.ctx)
        self._module.fit(
            train, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer, optimizer_params=self.kwargs,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback,
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params,
            allow_missing=self.arg_params is not None,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
            monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def _ensure_module(self, data):
        """Bind a Module lazily from saved params — load-then-infer is
        the legacy API's primary flow (reference binds a predictor the
        same way)."""
        if self._module is not None:
            return
        from .base import MXNetError
        from .module import Module
        if self.arg_params is None:
            raise MXNetError("FeedForward: call fit() or load() first")
        self._module = Module(self.symbol, context=self.ctx)
        self._module.bind(data_shapes=data.provide_data,
                          label_shapes=getattr(data, "provide_label", None),
                          for_training=False)
        self._module.set_params(self.arg_params, self.aux_params or {},
                                allow_missing=False, allow_extra=True)

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """(reference FeedForward.predict) — returns host numpy; with
        ``return_data`` also the concatenated data and labels."""
        import numpy as _np2
        data = self._as_iter(X)
        self._ensure_module(data)
        if not return_data:
            out = self._module.predict(data, num_batch=num_batch,
                                       reset=reset)
            if isinstance(out, list):  # multi-output symbol / empty iter
                return [o.asnumpy() for o in out]
            return out.asnumpy()
        if reset:
            data.reset()
        preds, xs, ys = [], [], []
        for i, batch in enumerate(data):
            if num_batch is not None and i >= num_batch:
                break
            self._module.forward(batch, is_train=False)
            keep = batch.data[0].shape[0] - (batch.pad or 0)
            preds.append(self._module.get_outputs()[0].asnumpy()[:keep])
            xs.append(batch.data[0].asnumpy()[:keep])
            if batch.label:
                ys.append(batch.label[0].asnumpy()[:keep])
        return (_np2.concatenate(preds), _np2.concatenate(xs),
                _np2.concatenate(ys) if ys else None)

    def score(self, X, eval_metric="acc", num_batch=None):
        data = self._as_iter(X)
        self._ensure_module(data)
        data.reset()
        return self._module.score(data, eval_metric,
                                  num_batch=num_batch)[0][1]

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None
                        else (self.num_epoch or 0), self.symbol,
                        self.arg_params, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               **kwargs):
        """(reference model.py:950 FeedForward.create) train-and-return."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            optimizer=optimizer, initializer=initializer,
                            **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger)
        return model
